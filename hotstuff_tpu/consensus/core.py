"""The 2-chain HotStuff core state machine (reference consensus/src/core.rs).

One actor owns ALL protocol state (round, last_voted_round, high_qc,
aggregator, pacemaker timer) and processes, via a single select loop
(core.rs:446-480):
  * Propose / Vote / Timeout / TC / SyncRequest messages from peers
  * LoopBack re-injections from the synchronizers
  * pacemaker timer expiry

Safety rules (core.rs:106-123): vote at most once per round, and only for a
block extending the latest QC (or justified by a TC). Liveness: the pacemaker
(timeout -> Timeout -> TC -> round advance with leader rotation).

Commit rule (2-chain, core.rs:344-350): committing b0 requires two blocks in
consecutive rounds, b0.round + 1 == b1.round, where b1 carries a QC on b0.

Improvement over the reference: the volatile safety state (round,
last_voted_round, high_qc) is persisted to the store and reloaded on restart,
closing the double-vote-after-crash gap the reference acknowledges
(consensus/src/core.rs:121, upstream issue #15).
"""

from __future__ import annotations

import asyncio
import logging
import time

from ..crypto import Digest, PublicKey, SignatureService, aggsig
from ..network import net
from ..network.net import NetMessage
from ..store import Store
from ..utils import metrics, tracing
from ..utils.actors import Selector, Timer, spawn
from ..utils.serde import Reader, Writer
from .aggregator import AggCertAggregator, Aggregator
from .config import Committee, Parameters
from .errors import (
    ConsensusError,
    InvalidSignatureError,
    WrongLeaderError,
    ensure,
)
from .leader import LeaderElector
from .mempool_driver import MempoolDriver
from .messages import (
    MAX_RANGE_BATCH,
    QC,
    TC,
    AggQC,
    AggTC,
    AggTimeoutBundle,
    AggVoteBundle,
    Block,
    LoopBack,
    Ping,
    Pong,
    Round,
    SyncRangeReply,
    SyncRangeRequest,
    SyncRequest,
    Timeout,
    TimeoutBundle,
    Vote,
    VoteBundle,
    _bitmap_members,
    _resolve_agg_keys,
    _timeout_digest,
    _vote_digest,
    decode_any_qc,
    decode_stored_block,
    encode_any_qc,
    encode_consensus_message,
    encode_stored_block,
)
from .overlay import (
    KIND_TIMEOUT,
    KIND_VOTE,
    OverlayRouter,
    filter_backed,
    note_plane_frames,
)
from .reconfig import EpochChange, MIN_ACTIVATION_MARGIN, as_manager
from .synchronizer import (
    RANGE_SYNC_THRESHOLD,
    RANGE_WALK_CAP,
    Synchronizer,
    collect_range,
)

log = logging.getLogger("hotstuff.consensus")

_SAFETY_KEY = b"safety-state"
# Leading-u64 sentinel marking the VERSIONED safety-state layout (round
# numbers never reach 2^64-1): the legacy layout cannot carry an AggQC
# high_qc, and legacy bytes must keep decoding byte-identically.
_SAFETY_AGG_SENTINEL = 0xFFFFFFFFFFFFFFFF

# Stage tracing for the protocol state machine (COMPONENTS.md metric table).
_M_PROPOSALS = metrics.counter("consensus.proposals")
_M_VOTES = metrics.counter("consensus.votes")
_M_COMMITS = metrics.counter("consensus.commits")
_M_TIMEOUTS = metrics.counter("consensus.timeouts")
_M_SYNC_SERVED = metrics.counter("consensus.sync_requests_served")
_M_ROUND = metrics.gauge("consensus.round")
_M_PROPOSAL_TO_VOTE = metrics.histogram("consensus.proposal_to_vote_s")
_M_COMMIT_LATENCY = metrics.histogram("consensus.commit_latency_s")
_M_RECONFIG_PROPOSED = metrics.counter("reconfig.proposed")
_M_HANDOFF_COMMITS = metrics.counter("reconfig.handoff_commits")
_M_RANGE_SERVED = metrics.counter("sync.range_served")
_M_RANGE_REPLIES = metrics.counter("sync.range_replies")
_M_RANGE_BLOCKS = metrics.counter("sync.range_blocks")
_M_PARKED = metrics.counter("sync.parked_blocks")
# Aggregate certificate plane (§5.5o). cert_bytes_committed counts the
# encoded certificate bytes of EVERY committed block regardless of mode,
# so legacy and aggregate matrix cells expose comparable
# bytes_per_committed_round columns (utils/telemetry.fleet_rollup).
_M_AGG_PARTIAL_REJECTS = metrics.counter("agg.partial_rejects")
_M_AGG_CERT_BYTES = metrics.counter("agg.cert_bytes_committed")
# Region-aware election attribution (§5.5p). Counted per COMMITTED round
# whenever a region map is wired (EVERY elector mode, so region-blind
# and region-aware cells expose comparable hop columns). The accounted
# leg is the commit-critical propose->certify PIVOT: round r's finished
# certificate reaching round r+1's proposer. Under leader-collector
# rooting that is a literal frame (the _handoff_qc bundle, leader r ->
# leader r+1); under next-leader rooting it is the last tree edge into
# the collector. Either way broadcast/tree frame TOTALS are placement-
# invariant under a population-proportional map; the pivot is the leg
# election placement actually controls. cross_region_hops counts pivots
# that crossed regions, leader_region_matches the co-located ones (they
# partition elect.rounds), and cross_region_hops_blind prices the SAME
# rounds under round-robin placement — a deterministic in-artifact
# counterfactual A/B.
_M_ELECT_ROUNDS = metrics.counter("elect.rounds")
_M_ELECT_MATCHES = metrics.counter("elect.leader_region_matches")
_M_ELECT_HOPS = metrics.counter("elect.cross_region_hops")
_M_ELECT_HOPS_BLIND = metrics.counter("elect.cross_region_hops_blind")

# Cap on the first-seen timestamp map feeding commit_latency_s: Byzantine
# proposals that never commit must not grow it without bound.
_SEEN_CAP = 4096


class Core:
    def __init__(
        self,
        name: PublicKey,
        committee: Committee,
        parameters: Parameters,
        signature_service: SignatureService,
        store: Store,
        leader_elector: LeaderElector,
        mempool_driver: MempoolDriver,
        synchronizer: Synchronizer,
        core_channel: asyncio.Queue,
        network_tx: asyncio.Queue,
        commit_channel: asyncio.Queue,
        verification_service=None,
        overlay_regions: dict[PublicKey, str] | None = None,
        agg_signer: "aggsig.AggSigner | None" = None,
        proof_registry=None,
    ) -> None:
        from ..crypto.batch_service import BatchVerificationService

        self.name = name
        # `committee` may be a static Committee or a reconfig.EpochManager;
        # either way the epoch manager is the single round -> committee
        # authority for this core (and is shared with the leader elector,
        # aggregator and synchronizer when wired by Consensus.run).
        self.epochs = as_manager(committee)
        self.parameters = parameters
        self.signature_service = signature_service
        # Off-loop batched verification: QC/TC/vote signature checks coalesce
        # into backend dispatches in a worker thread instead of blocking the
        # select loop (the seam the reference gets from tokio's threadpool).
        self.verification_service = (
            verification_service or BatchVerificationService()
        )
        self.store = store
        self.leader_elector = leader_elector
        self.mempool_driver = mempool_driver
        self.synchronizer = synchronizer
        self.core_channel = core_channel
        self.network_tx = network_tx
        self.commit_channel = commit_channel
        # Commit-proof serving plane (proofs/registry.py): when wired,
        # every committed block is indexed under its CERTIFYING
        # certificate — the successor's QC — so clients can be served
        # O(1) finality proofs (§5.5q).
        self.proofs = proof_registry

        self.round: Round = 1
        self.last_voted_round: Round = 0
        self.last_committed_round: Round = 0
        self.high_qc: QC | AggQC = QC.genesis()
        # Constant-size certificate plane (§5.5o): with aggregate_certs
        # on AND an aggregate signing key wired, this node's votes and
        # timeouts ride as singleton-bitmap partials and its quorums form
        # AggQC/AggTC. Inbound aggregate traffic is ALWAYS understood
        # (mixed-fleet interop); only the node's own emissions are gated.
        self.agg_signer = agg_signer
        self.agg = bool(parameters.aggregate_certs) and agg_signer is not None
        self.agg_aggregator = AggCertAggregator(
            self.epochs, window=parameters.agg_window
        )
        # Cumulative cert-plane commit stats feeding the "Cert plane:"
        # log line (benchmark LogParser's + CERTS section).
        self._agg_certs_committed = 0
        self._legacy_certs_committed = 0
        self._worst_cert_bytes = 0
        self._agg_depth_max = 0
        # Cumulative election-plane commit stats feeding the
        # "Election plane:" log line (benchmark LogParser's + ELECTION
        # section). Zero — and the line absent — without a region map.
        self._elect_rounds = 0
        self._elect_matches = 0
        self._elect_hops = 0
        self._elect_hops_blind = 0
        # The aggregator seeds verified vote/timeout signatures into the
        # service's dedup cache, so assembled QCs/TCs short-circuit.
        self.aggregator = Aggregator(self.epochs, self.verification_service)
        # Region-aware aggregation overlay (consensus/overlay.py). Always
        # constructed — inbound partial bundles merge regardless; whether
        # this node's OWN votes/timeouts ride the tree is gated by
        # Parameters.aggregation_overlay (default off, the committed
        # all-to-all baseline).
        self.overlay = OverlayRouter(self, overlay_regions)
        self.timer: Timer | None = None  # created inside the running loop
        # Newest TC this node processed or assembled: the lag-recovery
        # answer for a peer whose pacemaker is one round behind (see
        # _handle_timeout). TCs are otherwise fire-and-forget, and a
        # node that misses one stays a round behind the fleet for the
        # rest of a stall — fatal when the committee's quorum needs
        # every member (small post-churn committees).
        self.last_tc: TC | AggTC | None = None
        # Lag-recovery reply dedup: author -> (last_tc.round sent, when).
        # The stale-timeout branch deliberately spends no crypto, so an
        # unauthenticated flood forging a staked author could otherwise
        # reflect one full TC (O(n) signatures) per tiny frame at that
        # author's registered address; capping at one reply per (author,
        # TC round) per pacemaker period bounds the amplification to the
        # laggard's own honest re-timeout cadence while still re-serving
        # a reply the network dropped. Keys are stake-gated, so the map
        # is committee-bounded.
        self._lag_replies: dict[PublicKey, tuple[Round, float]] = {}
        # EpochChange queued for this node's next proposal (schedule_reconfig)
        self._pending_reconfig: EpochChange | None = None
        # Single-slot serve cache for chained range-sync batches:
        # (target digest bytes, walk floor, ancestor chain oldest-first).
        # Safe to reuse — a target's ancestry is immutable chain content.
        self._range_walk: tuple[bytes, Round, list[Block]] | None = None
        # Pacemaker backoff state: consecutive local timeouts without an
        # intervening QC-driven round advance (see Parameters.timeout_backoff).
        self._consecutive_timeouts = 0
        # block digest -> first-seen monotonic time, for commit_latency_s
        # (insertion-ordered; bounded by _SEEN_CAP, oldest evicted).
        self._block_seen: dict[Digest, float] = {}
        # Network-observatory probe sequence (see _probe_loop); runs only
        # when Parameters.probe_interval_ms > 0.
        self._probe_seq = 0

    @property
    def committee(self):
        """The committee governing the CURRENT round (epoch-resolved)."""
        return self.epochs.committee_for_round(self.round)

    def schedule_reconfig(self, change: EpochChange) -> None:
        """Queue a committee change for this node's next proposal. Carried
        until a proposal includes it; silently dropped once stale (the
        target epoch activated, or the activation round is no longer far
        enough ahead to commit first)."""
        self._pending_reconfig = change

    def _take_reconfig(self) -> EpochChange | None:
        change = self._pending_reconfig
        if change is None:
            return None
        if (
            change.new_epoch != self.epochs.applied_epoch + 1
            or change.activation_round < self.round + MIN_ACTIVATION_MARGIN
        ):
            self._pending_reconfig = None  # applied elsewhere, or too late
            return None
        if self.epochs.epoch_for_round(self.round) + 1 != change.new_epoch:
            # Applied but not yet ACTIVE predecessor boundary: a carrier
            # proposed now would ride a round the schedule still maps to
            # the pre-predecessor epoch and fail every replica's
            # sequencing check. Keep it queued until rounds cross the
            # previous activation boundary (the rolling-churn shape:
            # several EpochChanges in flight back to back).
            return None
        return change

    # -- persistence of safety-critical state (fixes reference issue #15) ----

    async def _load_safety_state(self) -> None:
        raw = await self.store.read(_SAFETY_KEY)
        if raw is None:
            return
        r = Reader(raw)
        first = r.u64()
        if first == _SAFETY_AGG_SENTINEL:
            # Versioned layout: the high_qc may be either certificate form.
            r.u8()  # layout version (1)
            self.round = r.u64()
            self.last_voted_round = r.u64()
            self.last_committed_round = r.u64()
            self.high_qc = decode_any_qc(r)
        else:
            self.round = first
            self.last_voted_round = r.u64()
            self.last_committed_round = r.u64()
            self.high_qc = QC.decode(r)
        log.info(
            "Recovered safety state: round %s, last_voted %s",
            self.round,
            self.last_voted_round,
        )

    async def _store_safety_state(self) -> None:
        w = Writer()
        if isinstance(self.high_qc, AggQC):
            # Sentinel-prefixed versioned layout; a legacy-form high_qc
            # keeps writing the historical bytes untouched.
            w.u64(_SAFETY_AGG_SENTINEL)
            w.u8(1)
            w.u64(self.round)
            w.u64(self.last_voted_round)
            w.u64(self.last_committed_round)
            encode_any_qc(w, self.high_qc)
        else:
            w.u64(self.round)
            w.u64(self.last_voted_round)
            w.u64(self.last_committed_round)
            self.high_qc.encode(w)
        await self.store.write(_SAFETY_KEY, w.bytes())

    # -- helpers -------------------------------------------------------------

    async def _transmit(
        self,
        msg,
        to: PublicKey | None,
        trace: "tracing.TraceContext | None" = None,
        urgent: bool = False,
    ) -> None:
        """Send to one authority, or broadcast to all others when to is None
        (consensus/src/synchronizer.rs:109-129 transmit helper). `trace`
        rides the frame trailer (utils/tracing.py) for cross-node
        commit-latency attribution. Direct sends resolve the address
        across every known epoch (a catch-up reply may target a peer only
        present in the adjacent epoch's committee); `urgent` selects the
        network's hot egress lane (sync recovery replies)."""
        data = encode_consensus_message(msg)
        if to is not None:
            addr = self.epochs.address(to)
            addrs = [addr] if addr else []
        else:
            addrs = self.committee.broadcast_addresses(self.name)
        if addrs:
            await self.network_tx.put(
                NetMessage(data, addrs, urgent=urgent, trace=trace)
            )

    @staticmethod
    def _trace_ctx(round_: Round, digest: Digest) -> "tracing.TraceContext | None":
        """Outbound trace context for block (round, digest); None with
        tracing disabled so the wire stays trailer-free."""
        if not tracing.enabled():
            return None
        return tracing.context_for(round_, digest.data)

    async def _store_block(self, block: Block) -> None:
        await self.store.write(block.digest().data, encode_stored_block(block))

    def _agg_bit(self, round_: Round) -> int | None:
        """This node's bit position in round_'s committee bitmap (sorted
        key order — the AggQC/AggTC convention); None when not a member
        of that round's committee."""
        keys = self.epochs.committee_for_round(round_).sorted_keys()
        try:
            return keys.index(self.name)
        except ValueError:
            return None

    # -- voting & committing -------------------------------------------------

    async def _make_vote(self, block: Block) -> Vote | AggVoteBundle | None:
        """Safety rules (core.rs:106-123), plus the epoch-final
        certification wall: while a next-epoch handoff is pending, this
        node refuses to help certify any round at or past the declared
        activation boundary — the old committee certifies THROUGH the
        epoch-final position and owns nothing after it, which is what
        makes a late-landing commit unable to re-map gap rounds
        (consensus/reconfig.py, §5.5j)."""
        if self.epochs.handoff_blocks(block.round):
            self.epochs.note_hold(block.round, "vote")
            return None
        safety_rule_1 = block.round > self.last_voted_round
        safety_rule_2 = block.qc.round + 1 == block.round
        if block.tc is not None:
            # TC justification: block jumps rounds but its QC is at least as
            # high as anything 2f+1 nodes saw when they timed out.
            ok_tc = (
                block.tc.round + 1 == block.round
                and block.qc.round >= max(block.tc.high_qc_rounds())
            )
            safety_rule_2 = safety_rule_2 or ok_tc
        if not (safety_rule_1 and safety_rule_2):
            return None
        self.last_voted_round = block.round
        await self._store_safety_state()
        digest = block.digest()
        if self.agg:
            # Aggregate mode: the vote IS a singleton-bitmap partial —
            # one aggregate-scheme signature over the same vote digest,
            # mergeable by any interior node on its way to the leader.
            bit = self._agg_bit(block.round)
            if bit is None:
                return None
            sig = self.agg_signer.sign(_vote_digest(digest, block.round).data)
            return AggVoteBundle(block.round, digest, 1 << bit, sig)
        signature = await self.signature_service.request_signature(
            _vote_digest(digest, block.round)
        )
        return Vote(digest, block.round, self.name, signature)

    async def _commit(self, block: Block, child: Block, grandchild: Block) -> None:
        """Commit `block` and all uncommitted ancestors, oldest first
        (core.rs:125-165). `child`/`grandchild` are the caller's b1 and
        the block under processing — the chain continuation above
        `block`, giving each committed EpochChange's LOCAL commit
        position for the late-apply observability check
        (reconfig.EpochManager.apply; the boundary itself stays the
        declared activation round)."""
        if self.last_committed_round >= block.round:
            return
        to_commit = [block]
        parent = block
        while True:
            parent_digest = parent.parent()
            if parent.qc.is_genesis():
                break
            raw = await self.store.read(parent_digest.data)
            if raw is None:
                log.error("missing ancestor during commit of %s", block)
                break
            parent = decode_stored_block(raw)
            if parent.round <= self.last_committed_round:
                break
            to_commit.append(parent)
        self.last_committed_round = block.round
        # Persist the floor BEFORE announcing the commit: the epoch-
        # boundary crash scenarios land a crash inside the commit path
        # (the switch hook fires here), and a floor that only becomes
        # durable at the NEXT vote would make the restarted node
        # re-commit its newest block — the monotonicity violation the
        # persisted safety state exists to prevent.
        await self._store_safety_state()
        # Commit-path synchronizer hygiene: the committed floor gates the
        # range-sync threshold, and fetches/waiters for branches at or
        # below it are abandoned forks to reclaim (the old leak).
        self.synchronizer.note_committed(block.round)
        self.synchronizer.cleanup(block.round)
        now = time.perf_counter()
        # to_commit is NEWEST-first: index i's chain grandchild is
        # to_commit[i-2], falling back to the caller's continuation for
        # the two newest entries. Applied oldest-first so stacked epoch
        # changes in one commit cascade sequence correctly.
        chain_above = {0: grandchild, 1: child}
        for i in range(len(to_commit) - 1, -1, -1):
            b = to_commit[i]
            if b.reconfig is not None:
                trigger = chain_above[i] if i < 2 else to_commit[i - 2]
                # The epoch-commit rule: the successor committee schedules
                # only HERE, when the carrying block is 2-chain committed
                # (apply is idempotent — a change can ride several blocks).
                await self.epochs.apply(
                    b.reconfig, store=self.store, trigger_round=trigger.round
                )
        # Handoff hygiene: a pending change whose every carrier the
        # committed chain just passed WITHOUT applying rode a dead fork —
        # drop it so its boundary stops walling certification.
        await self.epochs.note_commit(self.last_committed_round, store=self.store)
        for i in range(len(to_commit) - 1, -1, -1):
            b = to_commit[i]
            d = b.digest()
            _M_COMMITS.inc()
            self._note_cert_stats(b)
            self._note_election_stats(b)
            seen = self._block_seen.pop(d, None)
            if seen is not None:
                _M_COMMIT_LATENCY.record(now - seen)
            if tracing.enabled():
                tracing.event(
                    "commit",
                    tracing.trace_id(b.round, d.data),
                    (now - seen) if seen is not None else None,
                    round=b.round,
                )
            # NOTE: These log entries are used to compute performance.
            log.info("Committed B%s(%s)", b.round, d)
            for payload_digest in b.payload:
                log.info("Committed B%s(%s) -> %s", b.round, d, payload_digest)
            if self.proofs is not None:
                # The CERTIFYING certificate for to_commit[i] is the
                # successor block's carried QC (successor.qc.hash == d):
                # the 2-chain edge a stateless client can verify with
                # committee keys alone — exactly what the proof plane
                # serves (§5.5q).
                cert = (to_commit[i - 1] if i >= 1 else child).qc
                await self.proofs.note_commit(b, cert)
            await self.commit_channel.put(b)
        # NOTE: parsed by the benchmark LogParser (+ CERTS section).
        log.info(
            "Cert plane: %d aggregate / %d entry-list certs committed, "
            "worst cert %d B, agg depth %d",
            self._agg_certs_committed,
            self._legacy_certs_committed,
            self._worst_cert_bytes,
            self._agg_depth_max,
        )
        if self._elect_rounds:
            # NOTE: parsed by the benchmark LogParser (+ ELECTION section).
            log.info(
                "Election plane: %d round(s) committed, %d co-located "
                "pivot(s), %d cross-region hop(s), %d blind",
                self._elect_rounds,
                self._elect_matches,
                self._elect_hops,
                self._elect_hops_blind,
            )

    def _note_cert_stats(self, block: Block) -> None:
        """Per-committed-block certificate accounting: the encoded bytes
        feed agg.cert_bytes_committed (the fleet_rollup
        bytes_per_committed_round numerator, counted in EVERY mode so
        legacy and aggregate cells compare), the form split and worst
        size feed the cumulative "Cert plane:" line."""
        certs = [] if block.qc.is_genesis() else [block.qc]
        if block.tc is not None:
            certs.append(block.tc)
        for cert in certs:
            w = Writer()
            cert.encode(w)
            size = len(w.bytes())
            _M_AGG_CERT_BYTES.inc(size)
            if size > self._worst_cert_bytes:
                self._worst_cert_bytes = size
            if isinstance(cert, (AggQC, AggTC)):
                self._agg_certs_committed += 1
            else:
                self._legacy_certs_committed += 1

    def _note_election_stats(self, block: Block) -> None:
        """Per-committed-round election geometry (§5.5p): does the
        round's propose->certify pivot — its certificate travelling
        from round r's leader to round r+1's proposer (the _handoff_qc
        frame under leader-collector rooting) — stay inside one region?
        Scores the same pivot under round-robin placement as the blind
        counterfactual. Pure arithmetic over the frozen region map and
        the committed round — counters only, so same-seed replay stays
        bit-identical."""
        regions = self.overlay.region_of
        if not regions:
            return
        leader = self.leader_elector.get_leader(block.round)
        collector = self.leader_elector.get_leader(block.round + 1)
        self._elect_rounds += 1
        _M_ELECT_ROUNDS.inc()
        if regions.get(leader, "") == regions.get(collector, ""):
            self._elect_matches += 1
            _M_ELECT_MATCHES.inc()
        else:
            self._elect_hops += 1
            _M_ELECT_HOPS.inc()
        keys = self.epochs.schedule.sorted_keys_for_round(block.round)
        next_keys = self.epochs.schedule.sorted_keys_for_round(block.round + 1)
        blind_leader = keys[block.round % len(keys)]
        blind_collector = next_keys[(block.round + 1) % len(next_keys)]
        if regions.get(blind_leader, "") != regions.get(blind_collector, ""):
            self._elect_hops_blind += 1
            _M_ELECT_HOPS_BLIND.inc()

    # -- round pacing --------------------------------------------------------

    async def _process_qc(self, qc: QC | AggQC) -> None:
        """Adopt a higher QC and advance past its round (core.rs:263-276,321)."""
        if self.epochs.handoff_pending() and not qc.is_genesis():
            # Epoch-final commit unlock: with a handoff pending, the
            # observation that completes the carrier's 2-chain may never
            # arrive inside a block — when the completing pair hugs the
            # boundary, the QC on the pair's second block can only ride
            # a WALLED round's proposal; and a catch-up node may hold
            # the full pair plus its certificate (range-synced store +
            # a timeout's high_qc) while the wedged fleet produces no
            # further blocks at all. Commit straight off the adopted
            # certificate here; outside a pending handoff this path
            # never runs, so historical replay is byte-identical.
            await self._try_handoff_commit(qc)
        if qc.round > self.high_qc.round and tracing.enabled():
            # QC-assembly stage on NON-assembling nodes: the first time
            # this node sees a quorum certificate for the block.
            tracing.event(
                "qc", tracing.trace_id(qc.round, qc.hash.data), adopted=True
            )
        if qc.round >= self.round and self._consecutive_timeouts:
            # A QC advancing the round is real progress: restore the base
            # pacemaker delay. (TC-driven advances deliberately keep the
            # backed-off delay — a timeout round is not progress.)
            self._consecutive_timeouts = 0
            if self.timer is not None:
                self.timer.set_delay_ms(self.parameters.timeout_delay)
        await self._advance_round(qc.round)
        if qc.round > self.high_qc.round:
            self.high_qc = qc

    async def _try_handoff_commit(self, qc: QC) -> None:
        """Commit off an adopted certificate at the epoch-final edge: if
        `qc` certifies a stored block b1 whose own QC is consecutive
        (b0.round + 1 == b1.round), the 2-chain for b0 is complete — the
        observation normally arrives inside the NEXT block, which the
        wall may forbid. The commit trigger round is b1's (the round of
        the completing certificate), the honest local commit position."""
        if qc.round <= self.last_committed_round:
            return
        raw = await self.store.read(qc.hash.data)
        if raw is None:
            return
        b1 = decode_stored_block(raw)
        if b1.qc.is_genesis() or b1.qc.round + 1 != b1.round:
            return
        raw0 = await self.store.read(b1.parent().data)
        if raw0 is None:
            return
        b0 = decode_stored_block(raw0)
        if b0.round <= self.last_committed_round:
            return
        _M_HANDOFF_COMMITS.inc()
        log.info(
            "Handoff commit unlock: QC at round %s completes the 2-chain "
            "below the epoch boundary",
            qc.round,
        )
        await self._commit(b0, b1, b1)

    async def _advance_round(self, round_: Round) -> None:
        if round_ < self.round:
            return
        target = round_ + 1
        boundary = self.epochs.handoff_boundary()
        if boundary is not None and target > boundary:
            # Epoch-final wall, pacemaker side: while a handoff is
            # pending, this node may ENTER the boundary round (where the
            # successor committee's first traffic lands) but not cross
            # it — the rounds past the boundary belong to a committee it
            # has not committed yet. Crossing anyway (via old-committee
            # TCs formed during the stall) would strand it: everything
            # arriving at the boundary round becomes "stale", including
            # the very certificates whose fetch would complete its
            # handoff (the 64-node churn wedge).
            if boundary <= self.round:
                return
            target = boundary
        self.round = target
        _M_ROUND.set(self.round)
        # The epoch manager's current() (broadcast fan-out, synchronizer
        # peer picks) follows the newest round this core has reached.
        self.epochs.note_round(self.round)
        log.debug("Moved to round %s", self.round)
        if self.timer is not None:
            self.timer.reset()
        self.aggregator.cleanup(self.round)
        self.agg_aggregator.cleanup(self.round)
        self.overlay.cleanup(self.round)
        # Round/high_qc persistence piggybacks on the next pre-vote or
        # pre-timeout safety write (exactly one flushed write per round);
        # only last_voted_round must be durable BEFORE a signature leaves.

    async def _local_timeout_round(self) -> None:
        """Pacemaker fired (core.rs:175-197)."""
        _M_TIMEOUTS.inc()
        tracing.event(
            "timeout", round=self.round,
            consecutive=self._consecutive_timeouts + 1,
        )
        tracing.WATCHDOG.note_timeout(
            self.round, self._consecutive_timeouts + 1
        )
        log.warning("Timeout reached for round %s", self.round)
        self.last_voted_round = max(self.last_voted_round, self.round)
        await self._store_safety_state()
        agg_bit = self._agg_bit(self.round) if self.agg else None
        if agg_bit is not None:
            # Aggregate mode: a singleton-group partial (one group for
            # this node's high_qc round) carrying the backing certificate.
            sig = self.agg_signer.sign(
                _timeout_digest(self.round, self.high_qc.round).data
            )
            timeout: Timeout | AggTimeoutBundle = AggTimeoutBundle(
                self.round, self.high_qc,
                ((self.high_qc.round, 1 << agg_bit),), sig,
            )
        else:
            signature = await self.signature_service.request_signature(
                _timeout_digest(self.round, self.high_qc.round)
            )
            timeout = Timeout(self.high_qc, self.round, self.name, signature)
        if self.timer is not None:
            # Exponential backoff (liveness only — timeouts carry no safety
            # weight): under overload, firing at a fixed cadence adds
            # Timeout/TC verification storms to the very backlog that caused
            # the timeout. Growth starts at the THIRD consecutive timeout:
            # a single crashed leader inherently stalls two rounds per
            # rotation (the round whose votes it should collect, then its
            # own round), and backing off inside that ordinary 2-timeout
            # cycle would tax every crash-fault view change; only longer
            # chains (overload, partition) see growing delays. Restored by
            # the next QC-driven advance.
            self._consecutive_timeouts += 1
            p = self.parameters
            delay = min(
                p.timeout_delay
                * (p.timeout_backoff ** max(0, self._consecutive_timeouts - 2)),
                p.max_timeout_delay,
            )
            self.timer.set_delay_ms(max(delay, p.timeout_delay))
            self.timer.reset()
        if isinstance(timeout, AggTimeoutBundle):
            if self.overlay.enabled:
                await self.overlay.on_own_timeout_agg(timeout)
            else:
                await self._transmit(timeout, None)
                note_plane_frames(
                    KIND_TIMEOUT,
                    len(self.committee.broadcast_addresses(self.name)),
                )
            await self._handle_agg_timeout_bundle(timeout)
        elif self.overlay.enabled:
            # Overlay mode: ONE bundle frame up the round's aggregation
            # tree (plus a bounded gossip fallback if the round stays
            # stalled) instead of an n-1 frame broadcast — the O(n²)
            # timeout-storm fix (consensus/overlay.py).
            await self.overlay.on_own_timeout(timeout)
            await self._handle_timeout(timeout)
        else:
            await self._transmit(timeout, None)
            note_plane_frames(
                KIND_TIMEOUT,
                len(self.committee.broadcast_addresses(self.name)),
            )
            await self._handle_timeout(timeout)

    # -- proposals -----------------------------------------------------------

    async def _generate_proposal(self, tc: TC | AggTC | None) -> None:
        """Leader path (core.rs:278-318)."""
        if self.epochs.handoff_blocks(self.round):
            # Epoch-final wall, proposer side: nothing the old committee
            # proposes at or past a pending boundary may be certified, so
            # do not even ask — the round falls to the pacemaker until
            # the carrier's commit lands (then the successor committee
            # owns these rounds).
            self.epochs.note_hold(self.round, "proposal")
            return
        t0 = time.perf_counter()
        payload = await self.mempool_driver.get(self.parameters.max_payload_size)
        payload_dur = time.perf_counter() - t0
        reconfig = self._take_reconfig()
        digest = Block.make_digest(
            self.name, self.round, payload, self.high_qc, reconfig
        )
        signature = await self.signature_service.request_signature(digest)
        block = Block(
            self.high_qc, tc, self.name, self.round, tuple(payload), signature,
            reconfig,
        )
        _M_PROPOSALS.inc()
        if reconfig is not None:
            _M_RECONFIG_PROPOSED.inc()
            log.info(
                "Proposing %s in B%s", reconfig, block.round
            )
            # The proposer arms its OWN wall too: its proposal bypasses
            # _handle_proposal (it goes straight to _process_block), so
            # this is where the leader's pending handoff is recorded.
            await self.epochs.note_pending(
                reconfig, block.round, store=self.store
            )
        if tracing.enabled():
            tid = tracing.trace_id(block.round, digest.data)
            tracing.event("propose", tid, origin=True)
            # The leader's payload-fetch leg is the mempool Get above.
            tracing.event("payload", tid, payload_dur, digests=len(payload))
        if block.payload:
            # NOTE: This log entry is used to compute performance.
            log.info("Created B%s(%s)", block.round, block.digest())
        else:
            log.debug("Created empty %s", block)
        await self._transmit(block, None, trace=self._trace_ctx(block.round, digest))
        await self._process_block(block)

    async def _process_block(self, block: Block, replay: bool = False) -> None:
        """Ordering + commit logic (core.rs:327-378)."""
        t0 = time.perf_counter()
        ancestors = await self.synchronizer.get_ancestors(block)
        if ancestors is None:
            log.debug("processing of %s suspended: missing ancestors", block)
            return
        b0, b1 = ancestors
        await self._store_block(block)
        self._block_seen.setdefault(block.digest(), t0)
        while len(self._block_seen) > _SEEN_CAP:
            self._block_seen.pop(next(iter(self._block_seen)))

        # 2-chain commit rule.
        if b0.round + 1 == b1.round:
            await self._commit(b0, b1, block)
        await self.mempool_driver.cleanup(b0, b1, block)

        if replay or block.round != self.round:
            # Replayed (range-synced) blocks are historical: their QCs
            # already exist, so voting would only burn a signing + a
            # durable safety-state write + a stale frame per ancient
            # block — the round-match gate alone misses this on a node
            # whose round is still dragging up through the replay.
            return
        # NOTE: deliberately NO timer reset here. The pacemaker re-arms only
        # on round ADVANCE (core.rs:267-268): resetting on every current-round
        # block would let a Byzantine leader suppress this replica's Timeout
        # by re-sending its round-r proposal, and with f crashed replicas the
        # remaining honest timeouts could no longer reach 2f+1 for a TC.
        vote = await self._make_vote(block)
        if vote is None:
            return
        _M_VOTES.inc()
        _M_PROPOSAL_TO_VOTE.record(time.perf_counter() - t0)
        if tracing.enabled():
            tracing.event(
                "vote", tracing.trace_id(block.round, block.digest().data)
            )
        log.debug("created %s", vote)
        # Vote sink: the next leader (baseline — it needs the QC to
        # propose), or THIS round's leader under leader-collector mode
        # (§5.5p — the certificate forms in the proposing region and
        # hands off to the next proposer in one frame, _handoff_qc).
        sink = self.leader_elector.get_leader(
            self.round if self.parameters.leader_collector else self.round + 1
        )
        if isinstance(vote, AggVoteBundle):
            if sink == self.name:
                await self._handle_agg_vote_bundle(vote)
            elif self.overlay.enabled:
                await self.overlay.on_own_vote_agg(vote)
            else:
                await self._transmit(
                    vote, sink,
                    trace=self._trace_ctx(vote.round, vote.hash),
                )
                note_plane_frames(KIND_VOTE, 1)
            return
        if sink == self.name:
            await self._handle_vote(vote)
        elif self.overlay.enabled:
            # Overlay mode: the vote rides the region-aware tree rooted
            # at the sink — interior nodes merge partial bundles so the
            # collector's fan-in is O(fanout), not O(n).
            await self.overlay.on_own_vote(vote)
        else:
            await self._transmit(
                vote, sink,
                trace=self._trace_ctx(vote.round, vote.hash),
            )
            note_plane_frames(KIND_VOTE, 1)

    # -- message handlers ----------------------------------------------------

    async def _handle_proposal(self, block: Block, replay: bool = False) -> None:
        digest = block.digest()
        # Disabled-mode fast path: skip the trace-id formatting and the
        # extra clock reads entirely (tid=None keeps service groups untagged).
        traced = tracing.enabled()
        tid = tracing.trace_id(block.round, digest.data) if traced else None
        if traced:
            tracing.event("propose", tid)
        t0 = time.perf_counter()
        try:
            leader = self.leader_elector.get_leader(block.round)
            ensure(
                block.author == leader,
                WrongLeaderError(block.round, block.author, leader),
            )
            await block.verify_async(
                self.epochs, self.verification_service, trace=tid
            )
            if block.reconfig is not None:
                # Epoch sequencing + activation-margin admission (the
                # signature already rode the verify_async group).
                self.epochs.validate(block.reconfig, block.round)
                # Epoch-final handoff: an admitted carrier arms the
                # certification wall at its declared boundary until its
                # commit lands (persisted — a crash here must wake with
                # the wall intact).
                await self.epochs.note_pending(
                    block.reconfig, block.round, store=self.store
                )
        except ConsensusError:
            if (
                block.round > self.last_committed_round + RANGE_SYNC_THRESHOLD
                and await self.store.read(block.parent().data) is None
            ):
                # Catch-up seam: a block this far past our COMMITTED floor
                # may be certified by a committee epoch we have not
                # committed yet (reconfig.py), in which case every check
                # above judges it with stale epoch knowledge. Park it
                # unverified, fetch its claimed ancestry (range sync),
                # and re-validate from scratch when the parent arrives.
                # Nothing is trusted until that second pass succeeds. The
                # floor (not self.round) is the right yardstick: a joiner
                # admitted at an epoch boundary ADVANCES its round by
                # adopting certified high_qcs from the stall-round
                # timeouts around it while owning none of the chain — a
                # round-relative gate would then reject every proposal
                # (stale-epoch leader check) without ever fetching
                # ancestry, wedging the whole committee when the joiner
                # is needed for quorum. The parent-missing guard matters:
                # with the parent present this IS the second pass — a
                # failure now is genuine garbage, and re-parking it would
                # spin (the waiter fires instantly).
                if await self.synchronizer.fetch_unverified(block):
                    _M_PARKED.inc()
                    log.info(
                        "parking unverifiable B%s (%s rounds past the "
                        "committed floor %s) pending ancestry sync",
                        block.round,
                        block.round - self.last_committed_round,
                        self.last_committed_round,
                    )
                    return
            raise
        if traced:
            dur = time.perf_counter() - t0
            tracing.event("verify", tid, dur)
            if not block.qc.is_genesis():
                # Verifying this block also verified its embedded QC — the
                # verify leg of the PARENT block's lifecycle on this node.
                tracing.event(
                    "verify",
                    tracing.trace_id(block.qc.round, block.qc.hash.data),
                    dur,
                    via=tid,
                )
        await self._process_qc(block.qc)
        if block.tc is not None:
            self._note_tc(block.tc)
            await self._advance_round(block.tc.round)
        t0 = time.perf_counter()
        available = await self.mempool_driver.verify(block)
        if traced:
            tracing.event(
                "payload", tid, time.perf_counter() - t0, available=available
            )
        if not available:
            log.debug("%s waiting for payload availability", block)
            return
        await self._process_block(block, replay=replay)

    async def _handle_vote(self, vote: Vote) -> None:
        if vote.round < self.round:
            return
        traced = tracing.enabled()
        tid = tracing.trace_id(vote.round, vote.hash.data) if traced else None
        t0 = time.perf_counter()
        await vote.verify_async(
            self.epochs, self.verification_service, trace=tid
        )
        if traced:
            tracing.event("verify", tid, time.perf_counter() - t0, vote=True)
        qc = self.aggregator.add_vote(vote)
        if qc is not None:
            log.debug("assembled %s", qc)
            await self._process_qc(qc)
            if self.leader_elector.get_leader(self.round) == self.name:
                await self._generate_proposal(None)
            else:
                await self._handoff_qc(qc)

    async def _handoff_qc(self, qc: QC | AggQC) -> None:
        """Leader-collector handoff (§5.5p): this node collected round
        r's votes (it is round r's leader — Parameters.leader_collector
        roots the vote plane there) but round r+1's proposer sits
        elsewhere. The COMPLETE certificate rides one explicit bundle
        frame to the next leader, which re-verifies and assembles its
        own QC through the ordinary bundle handlers — no new message
        type, and the frame is the literal propose->certify pivot the
        elect.cross_region_hops counter prices. No-op outside
        leader-collector mode (the baseline's next-leader sink already
        holds the QC it needs)."""
        if not self.parameters.leader_collector:
            return
        next_leader = self.leader_elector.get_leader(qc.round + 1)
        if next_leader == self.name:
            return
        if hasattr(qc, "votes"):
            bundle = VoteBundle(qc.round, qc.hash, tuple(qc.votes))
        else:
            bundle = AggVoteBundle(qc.round, qc.hash, qc.bitmap, qc.agg_sig)
        note_plane_frames(KIND_VOTE, 1)
        await self._transmit(
            bundle, next_leader,
            urgent=True,
            trace=self._trace_ctx(qc.round, qc.hash),
        )

    def _note_tc(self, tc: TC | AggTC) -> None:
        if self.last_tc is None or tc.round > self.last_tc.round:
            self.last_tc = tc

    async def _handle_timeout(self, timeout: Timeout) -> None:
        if timeout.round < self.round:
            # Lag recovery: a timeout a few rounds behind us is the
            # signature of a peer that missed the TCs which advanced the
            # rest of the fleet (TCs are fire-and-forget). Re-serve our
            # newest TC directly — it advances the laggard past every
            # missed round in one hop. Without this, a committee whose
            # quorum needs the lagging members (post-churn committees,
            # joiners exiting their handoff a few stall-rounds behind)
            # wedges with each side re-timing-out rounds the other is
            # not in. Bounded: only lag within the range-sync threshold
            # (deeper lag rides the range-sync paths), only for a
            # claimed author with stake in the stale round's OR the
            # current round's committee (a joiner stuck at a boundary
            # is a member of the next epoch only), one direct frame per
            # received timeout, no crypto spent on the stale frame.
            now = asyncio.get_running_loop().time()
            prev = self._lag_replies.get(timeout.author)
            fresh = (
                prev is None
                or prev[0] != self.last_tc.round
                or (now - prev[1]) * 1000.0 >= self.parameters.timeout_delay
            ) if self.last_tc is not None else False
            if (
                fresh
                and timeout.round >= self.round - RANGE_SYNC_THRESHOLD
                and self.last_tc.round >= timeout.round
                and (
                    self.epochs.committee_for_round(timeout.round).stake(
                        timeout.author
                    )
                    > 0
                    or self.epochs.committee_for_round(self.round).stake(
                        timeout.author
                    )
                    > 0
                )
            ):
                self._lag_replies[timeout.author] = (self.last_tc.round, now)
                await self._transmit(self.last_tc, timeout.author, urgent=True)
            return
        try:
            await timeout.verify_async(self.epochs, self.verification_service)
        except ConsensusError:
            # Stale-epoch bootstrap (synchronizer.fetch_certified): a
            # timeout we cannot verify whose high_qc sits far past our
            # committed floor is the signature of a node that missed one
            # or more epoch boundaries — and when the committee needs
            # THIS node for quorum, these timeouts are the only traffic
            # that will ever arrive. Fetch the certified ancestry; the
            # replay installs the committed epoch switches, then live
            # traffic verifies. The timeout itself stays rejected.
            qc = timeout.high_qc
            if (
                not qc.is_genesis()
                and qc.round
                > self.last_committed_round + RANGE_SYNC_THRESHOLD
                and await self.synchronizer.fetch_certified(qc.hash, qc.round)
            ):
                _M_PARKED.inc()
                log.info(
                    "unverifiable timeout at round %s: bootstrapping range "
                    "sync from its high_qc (round %s, floor %s)",
                    timeout.round,
                    qc.round,
                    self.last_committed_round,
                )
                return
            raise
        await self._process_qc(timeout.high_qc)
        hqc = timeout.high_qc
        if (
            not hqc.is_genesis()
            and hqc.round > self.last_committed_round
            and await self.store.read(hqc.hash.data) is None
        ):
            # Certified-gap closure: this VERIFIED high_qc certifies a
            # block we never received. During a stall a node can run
            # ahead of its floor by adopting such certificates — and
            # once the whole committee waits on it at a boundary, no
            # future proposal will ever deliver the missing ancestry
            # (rounds cannot form without this node). Fetch the
            # certified block directly; its ancestry cascade and the
            # replayed epoch switches close the floor gap.
            await self.synchronizer.fetch_certified(hqc.hash, hqc.round)
        tc = self.aggregator.add_timeout(timeout)
        if tc is not None:
            log.debug("assembled %s", tc)
            self._note_tc(tc)
            await self._advance_round(tc.round)
            await self._transmit(tc, None)
            if self.leader_elector.get_leader(self.round) == self.name:
                await self._generate_proposal(tc)

    async def _handle_vote_bundle(self, bundle: VoteBundle) -> None:
        """Aggregation-overlay partial vote quorum (consensus/overlay.py).
        Unseen entries are batch-verified as ONE group on the scheduler's
        `aggregate` lane; an invalid entry rejects ALONE (counted in
        agg.invalid_entries) without poisoning the rest. The next leader
        feeds verified entries straight into its QC aggregator; everyone
        else merges and forwards one frame up the tree."""
        self.overlay.note_received()
        if bundle.round < self.round:
            return
        key = OverlayRouter.vote_key(bundle.round, bundle.hash)
        fresh = self.overlay.fresh(key, bundle.votes)
        if not fresh:
            return
        committee = self.epochs.committee_for_round(bundle.round)
        known = [(pk, sig) for pk, sig in fresh if committee.stake(pk) > 0]
        self.overlay.note_invalid(len(fresh) - len(known))
        if not known:
            return
        digest = _vote_digest(bundle.hash, bundle.round).data
        mask = await self.verification_service.verify_group(
            [digest] * len(known), known, committee=True, source="aggregate",
        )
        valid = [entry for entry, ok in zip(known, mask) if ok]
        self.overlay.note_invalid(len(known) - len(valid))
        new = self.overlay.merge(key, valid)
        if not new or bundle.round < self.round:
            return
        if self._vote_sink(bundle.round):
            for pk, sig in new:
                qc = self.aggregator.add_vote_entry(
                    bundle.round, bundle.hash, pk, sig
                )
                if qc is not None:
                    # NOTE: parsed by the benchmark LogParser (+ AGG:).
                    log.info(
                        "Agg bundle quorum: QC round %s from %s entries",
                        qc.round,
                        len(qc.votes),
                    )
                    await self._process_qc(qc)
                    if self.leader_elector.get_leader(self.round) == self.name:
                        await self._generate_proposal(None)
                    else:
                        await self._handoff_qc(qc)
                    return
        else:
            if await self._try_collector_quorum(key, bundle.round):
                return
            await self.overlay.after_merge(key)

    def _vote_sink(self, round_: Round) -> bool:
        """Is this node the vote-plane COLLECTOR for `round_` — the one
        assembler that feeds verified entries into its own QC
        aggregator? Exactly the node the round's tree roots at: the
        next leader (baseline — it needs the QC to propose) or the
        round's own leader under leader-collector mode (§5.5p). Nobody
        else may sink partials — under leader-collector the next leader
        sits INTERIOR in the round's tree, and swallowing its children's
        partials would starve the collector's subtree of quorum. The
        next leader instead assembles via the merged-state quorum watch
        (_try_collector_quorum) once the handoff frame lands."""
        return self.leader_elector.get_leader(
            round_ if self.parameters.leader_collector else round_ + 1
        ) == self.name

    async def _try_collector_quorum(self, key: tuple, round_: Round) -> bool:
        """Leader-collector quorum watch (§5.5p): the next proposer,
        merging vote partials as an ordinary interior node, assembles
        the certificate directly from merged overlay state the moment
        coverage reaches quorum — one merge after the collector's
        complete handoff bundle lands (or after fallback gossip
        delivers the same coverage the hard way). Returns True when a
        certificate was assembled and processed."""
        if not self.parameters.leader_collector or self.round > round_:
            return False
        if self.leader_elector.get_leader(round_ + 1) != self.name:
            return False
        committee = self.epochs.committee_for_round(round_)
        qc = self.overlay.quorum_certificate(key, committee)
        if qc is None:
            return False
        # NOTE: parsed by the benchmark LogParser (+ AGG:).
        log.info(
            "Agg bundle quorum: QC round %s from %s entries",
            qc.round,
            qc.signers() if hasattr(qc, "signers") else len(qc.votes),
        )
        await self._process_qc(qc)
        if self.leader_elector.get_leader(self.round) == self.name:
            await self._generate_proposal(None)
        return True

    async def _handle_timeout_bundle(self, bundle: TimeoutBundle) -> None:
        """Aggregation-overlay partial timeout quorum: entries and the
        carried high_qc verify as one `aggregate`-lane group (the QC is
        quorum-checked structurally first, like a Timeout's); any node
        that accumulates 2f+1 merged entries assembles the TC and
        broadcasts it — the storm-free replacement for every node
        broadcasting every Timeout."""
        self.overlay.note_received()
        if bundle.round < self.round:
            return
        key = OverlayRouter.timeout_key(bundle.round)
        fresh = self.overlay.fresh(key, bundle.timeouts)
        committee = self.epochs.committee_for_round(bundle.round)
        known = [entry for entry in fresh if committee.stake(entry[0]) > 0]
        self.overlay.note_invalid(len(fresh) - len(known))
        qc_ok: bool | None = bundle.high_qc.is_genesis()
        if not qc_ok:
            try:
                bundle.high_qc.check_quorum(self.epochs)
                qc_ok = None  # decided by the verification mask below
            except ConsensusError:
                self.overlay.note_invalid(1)
                qc_ok = False
        # Backing pre-filter: an entry's high_qc_round claim must be
        # covered by the bundle's carried QC (overlay.filter_backed — a
        # validly SIGNED but unbacked claim would poison every TC it
        # enters with an unsatisfiable justification round). Claims above
        # a structurally bad carried QC back to nothing (genesis only).
        backed_round = 0
        if qc_ok is not False and not bundle.high_qc.is_genesis():
            backed_round = bundle.high_qc.round
        known, unbacked = filter_backed(known, backed_round)
        self.overlay.note_invalid(unbacked)
        msgs = [
            _timeout_digest(bundle.round, hqr).data for _pk, _sig, hqr in known
        ]
        pairs: list = [(pk, sig) for pk, sig, _hqr in known]
        qc_lo = len(msgs)
        if qc_ok is None:
            m, p = bundle.high_qc.signed_items()
            msgs += m
            pairs += p
        if not msgs:
            return
        mask = await self.verification_service.verify_group(
            msgs, pairs, committee=True, source="aggregate",
        )
        valid = [entry for entry, ok in zip(known, mask[:qc_lo]) if ok]
        self.overlay.note_invalid(len(known) - len(valid))
        if qc_ok is None:
            qc_ok = all(mask[qc_lo:])
            if not qc_ok:
                self.overlay.note_invalid(1)
        if not qc_ok:
            # The carried QC's signatures failed AFTER the pre-filter
            # admitted claims against its round: those entries lost their
            # backing — only genesis claims survive.
            backed = [entry for entry in valid if entry[2] == 0]
            self.overlay.note_invalid(len(valid) - len(backed))
            valid = backed
        adopt_qc = qc_ok and not bundle.high_qc.is_genesis()
        new = self.overlay.merge(
            key, valid, high_qc=bundle.high_qc if adopt_qc else None
        )
        if adopt_qc:
            await self._process_qc(bundle.high_qc)
        if not new or bundle.round < self.round:
            return
        for pk, sig, hqr in new:
            tc = self.aggregator.add_timeout_entry(bundle.round, pk, sig, hqr)
            if tc is not None:
                # NOTE: parsed by the benchmark LogParser (+ AGG:).
                log.info(
                    "Agg bundle quorum: TC round %s from %s entries",
                    tc.round,
                    len(tc.votes),
                )
                self._note_tc(tc)
                await self._advance_round(tc.round)
                await self._transmit(tc, None)
                if self.leader_elector.get_leader(self.round) == self.name:
                    await self._generate_proposal(tc)
                return
        await self.overlay.after_merge(key)

    async def _handle_agg_vote_bundle(self, bundle: AggVoteBundle) -> None:
        """Aggregate-certificate vote partial (§5.5o). Verification is
        ATOMIC — the partial verifies as a whole or is rejected as a
        whole (Handel's rule: an aggregate has no per-entry signatures to
        salvage), so a forged member poisons only the partial carrying
        it. Verified partials feed the Handel packing state: the next
        leader's AggQCMaker when this node collects, the overlay partial
        set (merge + forward one frame up the tree) otherwise."""
        self.overlay.note_received()
        if bundle.round < self.round:
            return
        committee = self.epochs.committee_for_round(bundle.round)
        try:
            members = _bitmap_members(bundle.bitmap, committee)
            ensure(
                bool(members),
                InvalidSignatureError("empty aggregate vote partial"),
            )
            ok = aggsig.active_agg_scheme().verify(
                _resolve_agg_keys(members),
                bundle.signed_digest().data,
                bundle.agg_sig,
            )
            ensure(
                ok, InvalidSignatureError("aggregate vote partial rejected")
            )
        except ConsensusError:
            _M_AGG_PARTIAL_REJECTS.inc()
            self.overlay.note_invalid(1)
            raise
        if bundle.depth > self._agg_depth_max:
            self._agg_depth_max = bundle.depth
        if self._vote_sink(bundle.round):
            qc = self.agg_aggregator.add_vote_partial(bundle)
            if qc is not None:
                # NOTE: parsed by the benchmark LogParser (+ AGG:).
                log.info(
                    "Agg bundle quorum: QC round %s from %s entries",
                    qc.round,
                    qc.signers(),
                )
                await self._process_qc(qc)
                if self.leader_elector.get_leader(self.round) == self.name:
                    await self._generate_proposal(None)
                else:
                    await self._handoff_qc(qc)
            return
        key = OverlayRouter.vote_key(bundle.round, bundle.hash)
        self.overlay.merge_agg_vote(
            key, bundle.bitmap, bundle.agg_sig, bundle.depth
        )
        if await self._try_collector_quorum(key, bundle.round):
            return
        await self.overlay.after_merge(key)

    async def _handle_agg_timeout_bundle(self, bundle: AggTimeoutBundle) -> None:
        """Aggregate-certificate timeout partial. Atomicity REPLACES the
        legacy filter_backed per-entry salvage: a bundle whose max
        claimed high-qc round exceeds its carried certificate's round is
        rejected WHOLE (an honest sender never produces one), the
        carried certificate itself must verify, and the groups must be
        bitmap-disjoint — only then does the one aggregate signature get
        checked over the per-group timeout digests. Any node reaching
        2f+1 packed stake assembles the AggTC and broadcasts it."""
        self.overlay.note_received()
        if bundle.round < self.round:
            return
        committee = self.epochs.committee_for_round(bundle.round)
        try:
            ensure(
                bool(bundle.groups),
                InvalidSignatureError("empty aggregate timeout partial"),
            )
            claimed = max(hqr for hqr, _ in bundle.groups)
            ensure(
                claimed <= bundle.high_qc.round,
                InvalidSignatureError(
                    "aggregate timeout partial claims an unbacked high-qc "
                    f"round {claimed} > carried {bundle.high_qc.round}"
                ),
            )
            if not bundle.high_qc.is_genesis():
                await bundle.high_qc.verify_async(
                    self.epochs, self.verification_service
                )
            seen = 0
            groups = []
            for hqr, bm in bundle.groups:
                ensure(
                    not bm & seen,
                    InvalidSignatureError(
                        "overlapping groups in aggregate timeout partial"
                    ),
                )
                seen |= bm
                members = _bitmap_members(bm, committee)
                ensure(
                    bool(members),
                    InvalidSignatureError("empty aggregate timeout group"),
                )
                groups.append(
                    (
                        _resolve_agg_keys(members),
                        _timeout_digest(bundle.round, hqr).data,
                    )
                )
            ok = aggsig.active_agg_scheme().verify_groups(
                groups, bundle.agg_sig
            )
            ensure(
                ok, InvalidSignatureError("aggregate timeout partial rejected")
            )
        except ConsensusError:
            _M_AGG_PARTIAL_REJECTS.inc()
            self.overlay.note_invalid(1)
            raise
        if bundle.depth > self._agg_depth_max:
            self._agg_depth_max = bundle.depth
        if not bundle.high_qc.is_genesis():
            await self._process_qc(bundle.high_qc)
            if bundle.round < self.round:
                return  # the carried certificate already outran this round
        tc = self.agg_aggregator.add_timeout_partial(
            bundle.round, bundle.groups, bundle.agg_sig, bundle.depth
        )
        if tc is not None:
            # NOTE: parsed by the benchmark LogParser (+ AGG:).
            log.info(
                "Agg bundle quorum: TC round %s from %s entries",
                tc.round,
                tc.signers(),
            )
            self._note_tc(tc)
            await self._advance_round(tc.round)
            await self._transmit(tc, None)
            if self.leader_elector.get_leader(self.round) == self.name:
                await self._generate_proposal(tc)
            return
        key = OverlayRouter.timeout_key(bundle.round)
        self.overlay.merge_agg_timeout(
            key,
            bundle.groups,
            bundle.agg_sig,
            bundle.depth,
            carried_cert=bundle.high_qc,
        )
        await self.overlay.after_merge(key)

    async def _handle_tc(self, tc: TC | AggTC) -> None:
        """A TC received directly (core.rs:438-444)."""
        await tc.verify_async(self.epochs, self.verification_service)
        self._note_tc(tc)
        await self._advance_round(tc.round)
        if self.leader_elector.get_leader(self.round) == self.name:
            await self._generate_proposal(tc)

    async def _handle_sync_request(self, request: SyncRequest) -> None:
        """Re-send a stored block to a lagging peer (core.rs:418-436)."""
        raw = await self.store.read(request.digest.data)
        if raw is None:
            return
        _M_SYNC_SERVED.inc()
        block = decode_stored_block(raw)
        await self._transmit(block, request.requester, urgent=True)

    async def _handle_sync_range_request(self, request: SyncRangeRequest) -> None:
        """Serve a catch-up batch: the ancestor chain ending at the
        requested target, oldest-first, capped (synchronizer.collect_range).
        Unknown targets are ignored — the requester's retry escalation
        finds a peer that has it.

        A chained catch-up re-requests the SAME target with a rising
        from_round; re-walking the whole ancestry per batch would make
        the serve side quadratic in the gap (each walk reads+decodes up
        to the full chain to find the oldest 64 blocks). The single-slot
        walk cache keeps the full (walk-capped) chain for the last
        target: one walk per catch-up, a slice per batch."""
        cached = self._range_walk
        if (
            cached is not None
            and cached[0] == request.target.data
            and request.from_round >= cached[1]
        ):
            chain = cached[2]
        else:
            chain = await collect_range(
                self.store, request.target, request.from_round, cap=RANGE_WALK_CAP
            )
            self._range_walk = (request.target.data, request.from_round, chain)
        blocks = [b for b in chain if b.round > request.from_round][:MAX_RANGE_BATCH]
        if not blocks:
            return
        _M_RANGE_SERVED.inc()
        await self._transmit(
            SyncRangeReply(request.target, tuple(blocks)),
            request.requester,
            urgent=True,
        )

    async def _handle_sync_range_reply(self, reply: SyncRangeReply) -> None:
        """Ingest a catch-up batch. Every block runs the FULL proposal
        path (leader check, batched signature verification, per-epoch QC
        quorums, ordering, commit rule) in oldest-first order, so epoch
        switches committed mid-batch govern the validation of the blocks
        that follow them. A block that fails aborts the rest of the batch
        (later blocks depend on it); already-stored blocks are skipped, so
        duplicate replies from an escalated broadcast are cheap."""
        if not reply.blocks:
            return
        _M_RANGE_REPLIES.inc()
        processed = 0
        for block in reply.blocks:
            if await self.store.read(block.digest().data) is not None:
                continue
            try:
                await self._handle_proposal(block, replay=True)
            except ConsensusError as e:
                log.warning("range-sync block %s rejected: %s", block, e)
                break
            processed += 1
        if not processed:
            return
        _M_RANGE_BLOCKS.inc(processed)
        # NOTE: parsed by the benchmark LogParser (catch-up progress).
        log.info("Range sync fetched %s blocks", processed)
        if await self.store.read(reply.target.data) is None:
            # Still short of the target: chain the next batch eagerly off
            # the advanced committed floor instead of waiting out a retry.
            await self.synchronizer.continue_range(reply.target)
        else:
            log.info(
                "Range sync caught up: target %s resolved at round %s",
                reply.target.short(),
                self.last_committed_round,
            )

    # -- network observatory probes (network/net.py peer ledger) -------------

    # Peer-RTT-map log cadence: one summary per this many probe rounds
    # (the lines the benchmark LogParser's NETWORK section scrapes).
    PROBE_LOG_EVERY = 8

    async def _probe_loop(self) -> None:
        """Broadcast one Ping per Parameters.probe_interval_ms and fold
        the answering Pongs into the per-peer RTT EWMAs (network/net.py).
        Timestamps ride the loop clock, so under the chaos virtual-time
        loop every measured RTT — and therefore the whole ledger — is a
        pure function of the seed. Never spawned when the interval is 0:
        probe frames share the chaos transport's per-link fault streams
        with protocol traffic, so enabling them is a determinism-pin
        opt-in, not a default."""
        interval = self.parameters.probe_interval_ms / 1000.0
        loop = asyncio.get_running_loop()
        while True:
            await asyncio.sleep(interval)
            self._probe_seq += 1
            for addr in self.committee.broadcast_addresses(self.name):
                net.note_probe_sent(addr)
            ping = Ping(self.name, self._probe_seq, int(loop.time() * 1e6))
            await self._transmit(ping, None)
            if self._probe_seq % self.PROBE_LOG_EVERY == 0:
                self._log_peer_map()

    def _log_peer_map(self) -> None:
        # NOTE: these log entries are parsed by the benchmark LogParser.
        snap = net.peer_snapshot()
        rtts = {
            peer: s["rtt_ewma_ms"]
            for peer, s in snap.items()
            if s["rtt_ewma_ms"] is not None
        }
        sent = sum(s["probes_sent"] for s in snap.values())
        answered = sum(s["pongs_received"] for s in snap.values())
        if rtts:
            classes = net.rtt_classes(rtts)
            log.info(
                "Peer RTT map: %s peer(s) in %s class(es), worst EWMA %.3f ms",
                len(rtts),
                max(classes.values()) + 1,
                max(rtts.values()),
            )
        log.info("Probe summary: %s sent, %s answered", sent, answered)

    async def _handle_ping(self, ping: Ping) -> None:
        """Answer a peer's probe directly to its origin. Unsigned and
        stateless by design (see messages.Ping); an origin key outside
        every known epoch simply gets no reply."""
        addr = self.epochs.address(ping.origin)
        if addr is not None:
            net.note_ping_received(addr)
        await self._transmit(
            Pong(ping.origin, self.name, ping.seq, ping.sent_at_us), ping.origin
        )

    async def _handle_pong(self, pong: Pong) -> None:
        if pong.origin != self.name:
            return  # a misrouted (or forged) echo of someone else's probe
        addr = self.epochs.address(pong.responder)
        if addr is None:
            return
        rtt = (
            asyncio.get_running_loop().time() - pong.sent_at_us / 1e6
        )
        if rtt < 0:
            return  # echoed stamp from the future: not our clock's probe
        net.note_pong_rtt(addr, rtt)
        tracing.event(
            "net.probe",
            None,
            dur=rtt,
            peer=f"{addr[0]}:{addr[1]}",
            seq=pong.seq,
        )

    # -- main loop -----------------------------------------------------------

    async def run(self) -> None:
        await self._load_safety_state()
        # Rebuild committed epoch boundaries BEFORE processing traffic: a
        # node restarting past a committee switch must judge certificates
        # with the epoch knowledge its crashed incarnation had persisted.
        await self.epochs.load(self.store)
        self.epochs.note_round(self.round)
        self.synchronizer.note_committed(self.last_committed_round)
        self.timer = Timer(self.parameters.timeout_delay)
        if self.parameters.probe_interval_ms > 0:
            spawn(self._probe_loop(), name="consensus-probe")

        # Bootstrap: the round-1 leader proposes immediately (core.rs:446-454).
        if self.leader_elector.get_leader(self.round) == self.name:
            await self._generate_proposal(None)

        selector = Selector()
        selector.add("message", self.core_channel.get)
        # The pacemaker loses ties: a proposal already queued when the timer
        # expires must be processed first, or _local_timeout_round's
        # last_voted_round bump would withhold the vote for a block that
        # arrived in time (the reference's randomized select! has this race
        # half the time; here it is deterministic).
        selector.add("timer", self.timer.wait, priority=1)
        while True:
            branch, value = await selector.next()
            try:
                if branch == "timer":
                    # Discard stale expiries that raced a reset() (a message
                    # advancing the round may have completed the timer branch
                    # before the reset took effect).
                    if self.timer.expired():
                        await self._local_timeout_round()
                elif isinstance(value, Block):
                    await self._handle_proposal(value)
                elif isinstance(value, Vote):
                    await self._handle_vote(value)
                elif isinstance(value, Timeout):
                    await self._handle_timeout(value)
                elif isinstance(value, VoteBundle):
                    await self._handle_vote_bundle(value)
                elif isinstance(value, TimeoutBundle):
                    await self._handle_timeout_bundle(value)
                elif isinstance(value, AggVoteBundle):
                    await self._handle_agg_vote_bundle(value)
                elif isinstance(value, AggTimeoutBundle):
                    await self._handle_agg_timeout_bundle(value)
                elif isinstance(value, (TC, AggTC)):
                    await self._handle_tc(value)
                elif isinstance(value, SyncRequest):
                    await self._handle_sync_request(value)
                elif isinstance(value, SyncRangeRequest):
                    await self._handle_sync_range_request(value)
                elif isinstance(value, SyncRangeReply):
                    await self._handle_sync_range_reply(value)
                elif isinstance(value, Ping):
                    await self._handle_ping(value)
                elif isinstance(value, Pong):
                    await self._handle_pong(value)
                elif isinstance(value, LoopBack):
                    await self._process_block(value.block)
                else:
                    log.warning("unexpected core message: %r", value)
            except ConsensusError as e:
                log.warning("%s", e)
            except Exception as e:
                # A transient failure (e.g. a crypto-backend error surfaced
                # through verify_async) must not kill the consensus actor:
                # the message is dropped, the protocol's retry machinery
                # (pacemaker, sync tickers) recovers the state.
                log.error("consensus core error: %r", e)
