"""Dynamic validator reconfiguration: signed epoch changes, the
epoch-commit rule, and per-round committee resolution.

The operator set is no longer frozen at genesis (ROADMAP item 5). A
committee change travels THROUGH the chain as a signed `EpochChange`
carried by a proposal, and follows the epoch-commit rule of
deterministic-finality designs (PAPERS.md, arXiv:2512.09409): the new
committee takes effect only once the block carrying the change is
2-chain COMMITTED, and then only from the change's declared
`activation_round` onward. That gives every honest node the identical
round -> committee mapping (it is a pure function of committed chain
content), which is exactly what lets QC/TC quorums be verified against
the committee of the certificate's OWN epoch on both sides of a
boundary.

Pieces:

  * `EpochChange` — the wire payload: target epoch, activation round,
    the full successor member list (key, stake, address), signed by a
    current-epoch authority over a domain-separated digest. The block
    digest commits to it (see `Block.make_digest`), so a relay cannot
    strip or alter the change without invalidating the proposal.
  * `EpochSchedule` — the pure round -> committee map: an ordered list
    of (activation_round, committee) entries. Also used standalone by
    the chaos SafetyChecker, which re-derives its OWN schedule from the
    committed chain so invariant checking never trusts a node's state.
  * `EpochManager` — a node's live view: schedule + validation of
    proposed changes (epoch sequencing, activation margin), apply-on-
    commit with store persistence (a restarted node must rebuild the
    same mapping), current-committee resolution for transmit paths, and
    the device-side hook: at a switch the active crypto backend's
    committee table is re-registered (`register_committee`), whose
    snapshot-pinned tables let in-flight chunks finish on the OLD
    epoch (ops/ed25519.CommitteeTable, COMPONENTS.md §5.5c).

Liveness note: `activation_round` must trail the carrying block by at
least `MIN_ACTIVATION_MARGIN` rounds so the 2-chain commit lands before
the boundary under normal operation. A node that reaches the boundary
without the commit (it was crashed or partitioned) simply cannot verify
new-epoch certificates yet — that is the catch-up path's job (range
sync, consensus/synchronizer.py), not a safety hazard.
"""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass
from typing import Callable, Sequence

from ..crypto import Digest, PublicKey, Signature, sha512_32
from ..network.net import Address
from ..utils import metrics
from ..utils.serde import Reader, Writer
from .config import Authority, Committee
from .errors import ReconfigError, ensure

log = logging.getLogger("hotstuff.consensus")

Round = int

# A proposed change must place its boundary at least this many rounds
# past the carrying block, so the 2-chain commit normally lands first.
MIN_ACTIVATION_MARGIN = 3

_STORE_KEY = b"epoch-state"

_M_SWITCHES = metrics.counter("reconfig.epoch_switches")
_M_REJECTED = metrics.counter("reconfig.rejected")
_M_LATE_APPLIES = metrics.counter("reconfig.late_applies")
_M_EPOCH = metrics.gauge("reconfig.epoch")

Member = tuple[PublicKey, int, Address]  # (key, stake, address)


@dataclass(frozen=True, slots=True)
class EpochChange:
    """Signed committee-succession payload carried by a Block.

    `members` is the FULL successor set (join = new key present, leave =
    old key absent); stake and address ride along so quorum thresholds
    and broadcast fan-out recompute from the change alone. Signed by a
    current-epoch authority over a domain-separated digest."""

    new_epoch: int
    activation_round: Round
    members: tuple[Member, ...]
    author: PublicKey
    signature: Signature

    def digest(self) -> Digest:
        h = b"HSEPOCH" + _member_bytes(self.new_epoch, self.activation_round, self.members)
        return Digest(sha512_32(h + self.author.data))

    def committee(self) -> Committee:
        """The successor committee (quorum threshold recomputes from the
        member stakes via Committee.quorum_threshold)."""
        return Committee.new(list(self.members), epoch=self.new_epoch)

    @staticmethod
    def new_from_seed(
        new_epoch: int,
        activation_round: Round,
        members: Sequence[Member],
        author: PublicKey,
        seed: bytes,
    ) -> "EpochChange":
        """Construct + sign with a raw ed25519 seed (pysigner — the
        dependency-free path chaos and tests use)."""
        from ..crypto import pysigner

        change = EpochChange(
            new_epoch, activation_round, tuple(members), author, Signature(bytes(64))
        )
        sig = Signature(pysigner.sign(seed, change.digest().data))
        return EpochChange(new_epoch, activation_round, tuple(members), author, sig)

    def encode(self, w: Writer) -> None:
        w.u64(self.new_epoch)
        w.u64(self.activation_round)
        w.seq(
            list(self.members),
            lambda wr, m: (
                wr.fixed(m[0].data, 32),
                wr.u64(m[1]),
                wr.var_bytes(m[2][0].encode()),
                wr.u32(m[2][1]),
            ),
        )
        w.fixed(self.author.data, 32)
        w.fixed(self.signature.data, 64)

    @staticmethod
    def decode(r: Reader) -> "EpochChange":
        new_epoch = r.u64()
        activation_round = r.u64()
        members = tuple(
            r.seq(
                lambda rd: (
                    PublicKey(rd.fixed(32)),
                    rd.u64(),
                    (rd.var_bytes().decode(), rd.u32()),
                )
            )
        )
        return EpochChange(
            new_epoch,
            activation_round,
            members,
            PublicKey(r.fixed(32)),
            Signature(r.fixed(64)),
        )

    def __str__(self) -> str:
        return (
            f"EpochChange(epoch {self.new_epoch} @ round "
            f"{self.activation_round}, {len(self.members)} validators)"
        )


def _member_bytes(epoch: int, activation: Round, members: tuple[Member, ...]) -> bytes:
    w = Writer()
    w.u64(epoch)
    w.u64(activation)
    for pk, stake, addr in members:
        w.fixed(pk.data, 32)
        w.u64(stake)
        w.var_bytes(f"{addr[0]}:{addr[1]}".encode())
    return w.bytes()


class EpochSchedule:
    """Pure round -> committee map: ordered (activation_round, committee)
    entries, genesis at round 0. Appending is idempotent per epoch and
    strictly sequenced (epoch e+1 only extends epoch e)."""

    __slots__ = ("_entries",)

    def __init__(self, genesis: Committee) -> None:
        # (activation_round, committee, sorted keys) — keys cached: the
        # leader elector resolves every round through this list.
        self._entries: list[tuple[Round, Committee, list[PublicKey]]] = [
            (0, genesis, genesis.sorted_keys())
        ]

    @property
    def latest(self) -> Committee:
        return self._entries[-1][1]

    @property
    def latest_epoch(self) -> int:
        return self._entries[-1][1].epoch

    def entries(self) -> list[tuple[Round, Committee]]:
        return [(r, c) for r, c, _ in self._entries]

    def committee_for_round(self, round_: Round) -> Committee:
        for activation, committee, _keys in reversed(self._entries):
            if round_ >= activation:
                return committee
        return self._entries[0][1]

    def sorted_keys_for_round(self, round_: Round) -> list[PublicKey]:
        for activation, _committee, keys in reversed(self._entries):
            if round_ >= activation:
                return keys
        return self._entries[0][2]

    def epoch_for_round(self, round_: Round) -> int:
        return self.committee_for_round(round_).epoch

    def apply(self, activation_round: Round, committee: Committee) -> bool:
        """Append a boundary; False when already applied (idempotent) or
        out of sequence (an epoch may only succeed its predecessor)."""
        if committee.epoch != self.latest_epoch + 1:
            return False
        if activation_round <= self._entries[-1][0]:
            return False
        self._entries.append(
            (activation_round, committee, committee.sorted_keys())
        )
        return True


def as_manager(committee) -> "EpochManager":
    """Accept a Committee or an EpochManager wherever consensus components
    take one: a bare Committee wraps into a static single-epoch manager
    (the pre-reconfig behaviour, and what most unit tests pass)."""
    if isinstance(committee, EpochManager):
        return committee
    return EpochManager(committee)


class EpochManager:
    """A node's live epoch view: schedule + validation + apply-on-commit.

    One instance is shared by the Core, LeaderElector, Aggregator and
    Synchronizer of a node (consensus/consensus.py wires it), so a
    committed epoch change atomically moves leader rotation, quorum
    accounting and broadcast fan-out to the successor committee at the
    activation boundary."""

    def __init__(
        self,
        genesis: Committee,
        on_switch: Callable[[Committee, Round], None] | None = None,
        register_backend: bool = True,
    ) -> None:
        self.schedule = EpochSchedule(genesis)
        self._on_switch = [on_switch] if on_switch is not None else []
        self._register_backend = register_backend
        self._round_hint: Round = 1  # newest round the core has reached

    # -- resolution ---------------------------------------------------------

    @property
    def applied_epoch(self) -> int:
        return self.schedule.latest_epoch

    def committee_for_round(self, round_: Round) -> Committee:
        return self.schedule.committee_for_round(round_)

    def epoch_for_round(self, round_: Round) -> int:
        return self.schedule.epoch_for_round(round_)

    def current(self) -> Committee:
        """The committee governing the newest round the core reported
        (note_round) — what transmit paths broadcast against."""
        return self.schedule.committee_for_round(self._round_hint)

    def note_round(self, round_: Round) -> None:
        if round_ > self._round_hint:
            self._round_hint = round_

    def address(self, name: PublicKey) -> Address | None:
        """Resolve an authority address across every known epoch, newest
        first — a boundary-round reply may target a peer that is only in
        the adjacent epoch's committee."""
        for _activation, committee in reversed(self.schedule.entries()):
            addr = committee.address(name)
            if addr is not None:
                return addr
        return None

    def on_switch(self, hook: Callable[[Committee, Round], None]) -> None:
        self._on_switch.append(hook)

    # -- validation (proposal ingress) --------------------------------------

    def validate(self, change: EpochChange, block_round: Round) -> None:
        """Structural admission for an EpochChange riding a round-
        `block_round` proposal; raises ReconfigError. The author's
        signature is checked separately in Block.verify_async (it rides
        the block's batched service group)."""
        try:
            ensure(
                change.new_epoch == self.epoch_for_round(block_round) + 1,
                ReconfigError(
                    f"epoch change to {change.new_epoch} out of sequence "
                    f"(round {block_round} is epoch "
                    f"{self.epoch_for_round(block_round)})"
                ),
            )
            ensure(
                change.activation_round >= block_round + MIN_ACTIVATION_MARGIN,
                ReconfigError(
                    f"activation round {change.activation_round} inside the "
                    f"commit margin of round {block_round}"
                ),
            )
            ensure(
                len(change.members) > 0,
                ReconfigError("epoch change with an empty committee"),
            )
            committee = change.committee()
            ensure(
                committee.total_votes() > 0,
                ReconfigError("epoch change with zero total stake"),
            )
        except ReconfigError:
            _M_REJECTED.inc()
            raise

    # -- apply-on-commit -----------------------------------------------------

    async def apply(
        self, change: EpochChange, store=None, trigger_round: Round | None = None
    ) -> bool:
        """Epoch-commit rule: called only once the carrying block is
        2-chain committed. Idempotent (a change committed in two blocks,
        or re-applied from persistence, is a no-op the second time).

        The boundary is ALWAYS the DECLARED activation round — pure
        chain content, so every node (live, restarting, or replaying a
        range-synced chain) derives the identical round -> committee
        map. The block that locally completes the carrier's 2-chain is
        deliberately NOT folded in: two nodes can first see different
        QC-carrying envelopes (one of which may never certify), so any
        trigger-derived boundary would diverge across honest nodes — a
        schedule split, the one thing the epoch-commit rule exists to
        prevent.

        The margin contract is what keeps the declared round sound: the
        commit normally lands well before the boundary (activation must
        trail the carrier by MIN_ACTIVATION_MARGIN; proposers should
        size the real margin against worst-case consecutive round
        failures — the chaos directive uses 10). If the commit is
        nevertheless delayed past the boundary (>= margin-2 consecutive
        failed rounds inside the window), certificates formed in the
        gap were certified by the old committee but are judged by the
        new one — `trigger_round` (the caller's local commit position)
        makes that pathology loudly observable (`reconfig.late_applies`)
        instead of silent. ROADMAP item 5 records it as an open
        residue."""
        committee = change.committee()
        if not self.schedule.apply(change.activation_round, committee):
            return False
        if (
            trigger_round is not None
            and trigger_round >= change.activation_round
        ):
            _M_LATE_APPLIES.inc()
            log.warning(
                "epoch %s applied LATE: commit landed at round %s, past "
                "the declared activation round %s — certificates in the "
                "gap were formed under the old committee (size the "
                "activation margin against consecutive round failures)",
                committee.epoch,
                trigger_round,
                change.activation_round,
            )
        self._switched(committee, change.activation_round)
        if store is not None:
            await self.save(store)
        return True

    def _switched(self, committee: Committee, activation_round: Round) -> None:
        _M_SWITCHES.inc()
        _M_EPOCH.set(committee.epoch)
        # NOTE: this log entry is parsed by the benchmark LogParser.
        log.info(
            "Epoch switch to %s at activation round %s (%s validators, quorum %s)",
            committee.epoch,
            activation_round,
            committee.size(),
            committee.quorum_threshold(),
        )
        self._reregister(committee)
        for hook in self._on_switch:
            hook(committee, activation_round)

    def _reregister(self, committee: Committee) -> None:
        """Device-side committee succession: swap the backend's resident
        key tables to the new epoch. TpuBackend registration is snapshot-
        pinned (ops/ed25519.CommitteeTable): batches staged against the
        old table finish on the OLD epoch's replicas while new traffic
        resolves against the new indices — no flush barrier needed."""
        if not self._register_backend:
            return
        from ..crypto import get_backend

        backend = get_backend()
        if hasattr(backend, "register_committee"):
            try:
                backend.register_committee(committee.sorted_keys())
            except Exception as e:  # registration is an optimization only
                log.warning("epoch committee re-registration failed: %r", e)

    # -- persistence ---------------------------------------------------------

    async def save(self, store) -> None:
        entries = [
            {"activation_round": r, "committee": c.to_json()}
            for r, c in self.schedule.entries()[1:]  # genesis comes from config
        ]
        await store.write(_STORE_KEY, json.dumps(entries).encode())

    async def load(self, store) -> None:
        """Rebuild applied boundaries after a restart (idempotent). The
        switch hooks re-fire so the backend tables match the persisted
        epoch before the node rejoins."""
        raw = await store.read(_STORE_KEY)
        if raw is None:
            return
        for entry in json.loads(raw.decode()):
            committee = Committee.from_json(entry["committee"])
            if self.schedule.apply(entry["activation_round"], committee):
                self._switched(committee, entry["activation_round"])
