"""Dynamic validator reconfiguration: signed epoch changes, the
epoch-commit rule, the EPOCH-FINAL HANDOFF, and per-round committee
resolution.

The operator set is no longer frozen at genesis (ROADMAP item 5). A
committee change travels THROUGH the chain as a signed `EpochChange`
carried by a proposal, and follows the epoch-commit rule of
deterministic-finality designs (PAPERS.md, arXiv:2512.09409): the new
committee takes effect only once the block carrying the change is
2-chain COMMITTED, and then only from the change's declared
`activation_round` onward. That gives every honest node the identical
round -> committee mapping (it is a pure function of committed chain
content), which is exactly what lets QC/TC quorums be verified against
the committee of the certificate's OWN epoch on both sides of a
boundary.

THE EPOCH-FINAL HANDOFF (COMPONENTS.md §5.5j). The carrying block is an
epoch-final position: the old committee certifies THROUGH the declared
boundary minus one and owns nothing at or past it. PR 10 left a named
hazard — a 2-chain commit delayed past the declared activation meant
rounds in the gap [activation, commit] had already been certified by the
OLD committee but were re-judged by the new one once the late apply
landed (`reconfig.late_applies`, then only a warning). The handoff makes
that impossible BY CONSTRUCTION rather than merely observable:

  * every honest node that PROCESSES a carrier records the change as a
    PENDING HANDOFF (`note_pending`, persisted with the epoch state so a
    crash at the boundary cannot forget it);
  * while a next-epoch handoff is pending, the node refuses to vote for
    or propose blocks at rounds >= the declared activation round — the
    certification WALL (`handoff_blocks`, enforced in Core._make_vote /
    _generate_proposal, counted in `reconfig.handoff_holds`). A carrier
    that got CERTIFIED was voted by >= quorum nodes, so >= f+1 honest
    nodes hold the wall and no old-committee quorum can form in the gap;
  * the commit therefore completes strictly below the boundary (the
    chain stalls at activation-1 until it does — Core._try_handoff_commit
    unwedges the one edge where the completing QC can no longer ride a
    block), and `reconfig.late_applies` is now a HARD invariant: the
    chaos SafetyChecker derives the same epoch-final schedule from chain
    content alone and flags any chain where a carrier was not
    2-chain-final before its activation round;
  * a pending whose carrier fork DIES (the chain commits past the
    carrier round without it) is dropped (`note_commit`,
    `reconfig.handoff_abandoned`) so a never-committed change cannot
    wall liveness forever.

Pieces:

  * `EpochChange` — the wire payload: target epoch, activation round,
    the full successor member list (key, stake, consensus address,
    MEMPOOL address — the payload plane crosses the boundary with the
    same change), signed by a current-epoch authority over a
    domain-separated digest. The block digest commits to it (see
    `Block.make_digest`), so a relay cannot strip or alter the change
    without invalidating the proposal.
  * `EpochSchedule` — the pure round -> committee map: an ordered list
    of (activation_round, committee) entries. Also used standalone by
    the chaos SafetyChecker, which re-derives its OWN schedule from the
    committed chain so invariant checking never trusts a node's state.
  * `EpochManager` — a node's live view: schedule + pending handoffs +
    validation of proposed changes (epoch sequencing, activation
    margin), apply-on-commit with store persistence (a restarted node
    must rebuild the same mapping AND the same wall), current-committee
    resolution for transmit paths, the per-epoch mempool address
    registry the MempoolEpochView resolves through, and the device-side
    hook: at a switch the active crypto backend's committee table is
    re-registered (`register_committee`), whose snapshot-pinned tables
    let in-flight chunks finish on the OLD epoch (ops/ed25519
    CommitteeTable, COMPONENTS.md §5.5c).

Liveness note: `activation_round` must trail the carrying block by at
least `MIN_ACTIVATION_MARGIN` rounds so the 2-chain commit lands before
the boundary under normal operation. Under the wall a margin violation
costs LIVENESS at the boundary (rounds stall at activation-1 until the
commit completes via sync), never safety — the explicit trade the
epoch-final contract makes.
"""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass, field
from typing import Callable, Sequence

from ..crypto import Digest, PublicKey, Signature, sha512_32
from ..network.net import Address
from ..utils import metrics, tracing
from ..utils.serde import Reader, SerdeError, Writer
from .config import Committee
from .errors import ReconfigError, ensure

log = logging.getLogger("hotstuff.consensus")

Round = int

# A proposed change must place its boundary at least this many rounds
# past the carrying block, so the 2-chain commit normally lands first.
MIN_ACTIVATION_MARGIN = 3

# Decode cap on successor members: an EpochChange rides unauthenticated
# proposal frames, and a receiver must not materialize an unbounded
# member list (each entry costs a key + stake + two addresses).
MAX_WIRE_MEMBERS = 4_096

_STORE_KEY = b"epoch-state"

_M_SWITCHES = metrics.counter("reconfig.epoch_switches")
_M_REJECTED = metrics.counter("reconfig.rejected")
_M_LATE_APPLIES = metrics.counter("reconfig.late_applies")
_M_EPOCH = metrics.gauge("reconfig.epoch")
_M_HANDOFF_HOLDS = metrics.counter("reconfig.handoff_holds")
_M_HANDOFF_ABANDONED = metrics.counter("reconfig.handoff_abandoned")
# Rounds the commit trigger landed past the LAST old-committee round
# (activation-1): 0 on every healthy handoff, >=1 exactly when the
# epoch-final contract was violated — the telemetry SLO row keys on it.
_M_HANDOFF_LAG = metrics.histogram(
    "reconfig.handoff_lag_rounds", (0.5, 2.0, 8.0, 32.0)
)

# (key, stake, consensus address, mempool address). The mempool address
# is what makes the payload plane's succession possible: a joiner's
# payloads are fetchable only once peers can resolve its mempool port,
# and that fact must travel in the SAME chain content as the committee
# change (a side channel could desynchronize the two planes).
Member = tuple[PublicKey, int, Address, Address]


def _normalize_members(members: Sequence) -> tuple[Member, ...]:
    """Accept (key, stake, address) triples for single-plane callers and
    tests — the mempool address then mirrors the consensus address —
    while the wire format always carries the full 4-tuple."""
    out: list[Member] = []
    for m in members:
        if len(m) == 3:
            pk, stake, addr = m
            out.append((pk, stake, addr, addr))
        else:
            pk, stake, addr, maddr = m
            out.append((pk, stake, addr, maddr))
    return tuple(out)


@dataclass(frozen=True, slots=True)
class EpochChange:
    """Signed committee-succession payload carried by a Block.

    `members` is the FULL successor set (join = new key present, leave =
    old key absent); stake and both plane addresses ride along so quorum
    thresholds, broadcast fan-out AND payload-gossip fan-out recompute
    from the change alone. Signed by a current-epoch authority over a
    domain-separated digest."""

    new_epoch: int
    activation_round: Round
    members: tuple[Member, ...]
    author: PublicKey
    signature: Signature

    def __post_init__(self) -> None:
        object.__setattr__(
            self, "members", _normalize_members(self.members)
        )

    def digest(self) -> Digest:
        h = b"HSEPOCH" + _member_bytes(self.new_epoch, self.activation_round, self.members)
        return Digest(sha512_32(h + self.author.data))

    def committee(self) -> Committee:
        """The successor CONSENSUS committee (quorum threshold recomputes
        from the member stakes via Committee.quorum_threshold)."""
        return Committee.new(
            [(pk, stake, addr) for pk, stake, addr, _maddr in self.members],
            epoch=self.new_epoch,
        )

    def mempool_addresses(self) -> dict[PublicKey, Address]:
        """The successor's payload-plane ports (MempoolEpochView feed)."""
        return {pk: maddr for pk, _stake, _addr, maddr in self.members}

    @staticmethod
    def new_from_seed(
        new_epoch: int,
        activation_round: Round,
        members: Sequence,
        author: PublicKey,
        seed: bytes,
    ) -> "EpochChange":
        """Construct + sign with a raw ed25519 seed (pysigner — the
        dependency-free path chaos and tests use)."""
        from ..crypto import pysigner

        change = EpochChange(
            new_epoch, activation_round, tuple(members), author, Signature(bytes(64))
        )
        sig = Signature(pysigner.sign(seed, change.digest().data))
        return EpochChange(new_epoch, activation_round, change.members, author, sig)

    def encode(self, w: Writer) -> None:
        w.u64(self.new_epoch)
        w.u64(self.activation_round)
        w.seq(
            list(self.members),
            lambda wr, m: (
                wr.fixed(m[0].data, 32),
                wr.u64(m[1]),
                wr.var_bytes(m[2][0].encode()),
                wr.u32(m[2][1]),
                wr.var_bytes(m[3][0].encode()),
                wr.u32(m[3][1]),
            ),
        )
        w.fixed(self.author.data, 32)
        w.fixed(self.signature.data, 64)

    @staticmethod
    def decode(r: Reader) -> "EpochChange":
        new_epoch = r.u64()
        activation_round = r.u64()
        # Cap checked on the COUNT, before materializing a single member:
        # an unauthenticated proposal frame must not make the receiver
        # allocate an oversized member list only to throw it away.
        count = r.u32()
        if count > MAX_WIRE_MEMBERS:
            raise SerdeError(f"epoch change over member cap: {count}")
        members = tuple(
            (
                PublicKey(r.fixed(32)),
                r.u64(),
                (r.var_bytes().decode(), r.u32()),
                (r.var_bytes().decode(), r.u32()),
            )
            for _ in range(count)
        )
        return EpochChange(
            new_epoch,
            activation_round,
            members,
            PublicKey(r.fixed(32)),
            Signature(r.fixed(64)),
        )

    def __str__(self) -> str:
        return (
            f"EpochChange(epoch {self.new_epoch} @ round "
            f"{self.activation_round}, {len(self.members)} validators)"
        )


def _member_bytes(epoch: int, activation: Round, members: tuple[Member, ...]) -> bytes:
    w = Writer()
    w.u64(epoch)
    w.u64(activation)
    for pk, stake, addr, maddr in members:
        w.fixed(pk.data, 32)
        w.u64(stake)
        w.var_bytes(f"{addr[0]}:{addr[1]}".encode())
        w.var_bytes(f"{maddr[0]}:{maddr[1]}".encode())
    return w.bytes()


class EpochSchedule:
    """Pure round -> committee map: ordered (activation_round, committee)
    entries, genesis at round 0. Appending is idempotent per epoch and
    strictly sequenced (epoch e+1 only extends epoch e)."""

    __slots__ = ("_entries",)

    def __init__(self, genesis: Committee) -> None:
        # (activation_round, committee, sorted keys) — keys cached: the
        # leader elector resolves every round through this list.
        self._entries: list[tuple[Round, Committee, list[PublicKey]]] = [
            (0, genesis, genesis.sorted_keys())
        ]

    @property
    def latest(self) -> Committee:
        return self._entries[-1][1]

    @property
    def latest_epoch(self) -> int:
        return self._entries[-1][1].epoch

    def entries(self) -> list[tuple[Round, Committee]]:
        return [(r, c) for r, c, _ in self._entries]

    def committee_for_round(self, round_: Round) -> Committee:
        for activation, committee, _keys in reversed(self._entries):
            if round_ >= activation:
                return committee
        return self._entries[0][1]

    def sorted_keys_for_round(self, round_: Round) -> list[PublicKey]:
        for activation, _committee, keys in reversed(self._entries):
            if round_ >= activation:
                return keys
        return self._entries[0][2]

    def epoch_for_round(self, round_: Round) -> int:
        return self.committee_for_round(round_).epoch

    def apply(self, activation_round: Round, committee: Committee) -> bool:
        """Append a boundary; False when already applied (idempotent) or
        out of sequence (an epoch may only succeed its predecessor)."""
        if committee.epoch != self.latest_epoch + 1:
            return False
        if activation_round <= self._entries[-1][0]:
            return False
        self._entries.append(
            (activation_round, committee, committee.sorted_keys())
        )
        return True


def as_manager(committee) -> "EpochManager":
    """Accept a Committee or an EpochManager wherever consensus components
    take one: a bare Committee wraps into a static single-epoch manager
    (the pre-reconfig behaviour, and what most unit tests pass)."""
    if isinstance(committee, EpochManager):
        return committee
    return EpochManager(committee)


@dataclass(slots=True)
class _PendingHandoff:
    """One admitted-but-uncommitted EpochChange: the wall's unit of
    state. `carriers` is the set of block rounds observed carrying this
    change (one change can ride several leaders' proposals); the pending
    dies only when the committed chain passes EVERY carrier without the
    change applying — that fork lost, the boundary is void."""

    change: EpochChange
    carriers: set = field(default_factory=set)


class EpochManager:
    """A node's live epoch view: schedule + pending handoffs +
    validation + apply-on-commit.

    One instance is shared by the Core, LeaderElector, Aggregator and
    Synchronizer of a node (consensus/consensus.py wires it) AND by the
    mempool plane's MempoolEpochView (mempool/config.py), so a committed
    epoch change atomically moves leader rotation, quorum accounting,
    broadcast fan-out and payload-gossip fan-out to the successor
    committee at the same activation boundary."""

    def __init__(
        self,
        genesis: Committee,
        on_switch: Callable[[Committee, Round], None] | None = None,
        register_backend: bool = True,
    ) -> None:
        self.schedule = EpochSchedule(genesis)
        self._on_switch = [on_switch] if on_switch is not None else []
        self._register_backend = register_backend
        self._round_hint: Round = 1  # newest round the core has reached
        # Pending epoch-final handoffs, keyed by change digest bytes.
        self._pending: dict[bytes, _PendingHandoff] = {}
        # Payload-plane address registry: genesis entries seeded by the
        # MempoolEpochView, successors learned from applied EpochChanges
        # (and persisted with the epoch state). Addresses accumulate —
        # a DEPARTED member stays resolvable so its stored payloads can
        # still be fetched for old blocks.
        self._mempool_addrs: dict[PublicKey, Address] = {}

    # -- resolution ---------------------------------------------------------

    @property
    def applied_epoch(self) -> int:
        return self.schedule.latest_epoch

    def committee_for_round(self, round_: Round) -> Committee:
        return self.schedule.committee_for_round(round_)

    def epoch_for_round(self, round_: Round) -> int:
        return self.schedule.epoch_for_round(round_)

    def current(self) -> Committee:
        """The committee governing the newest round the core reported
        (note_round) — what transmit paths broadcast against."""
        return self.schedule.committee_for_round(self._round_hint)

    def note_round(self, round_: Round) -> None:
        if round_ > self._round_hint:
            self._round_hint = round_

    def address(self, name: PublicKey) -> Address | None:
        """Resolve an authority address across every known epoch, newest
        first — a boundary-round reply may target a peer that is only in
        the adjacent epoch's committee."""
        for _activation, committee in reversed(self.schedule.entries()):
            addr = committee.address(name)
            if addr is not None:
                return addr
        return None

    def on_switch(self, hook: Callable[[Committee, Round], None]) -> None:
        self._on_switch.append(hook)

    # -- payload-plane address registry -------------------------------------

    def seed_mempool_addresses(self, addrs: dict[PublicKey, Address]) -> None:
        """Install genesis payload-plane ports (MempoolEpochView calls
        this once at wiring time); applied EpochChanges extend the map."""
        for pk, addr in addrs.items():
            self._mempool_addrs.setdefault(pk, addr)

    def mempool_address(self, name: PublicKey) -> Address | None:
        return self._mempool_addrs.get(name)

    # -- epoch-final handoff (the wall) --------------------------------------

    def handoff_boundary(self) -> Round | None:
        """The earliest declared activation round among pending NEXT-epoch
        changes, or None when no handoff is in flight. Rounds at or past
        it are walled until the carrier commits."""
        best: Round | None = None
        nxt = self.applied_epoch + 1
        for p in self._pending.values():
            if p.change.new_epoch == nxt and (
                best is None or p.change.activation_round < best
            ):
                best = p.change.activation_round
        return best

    def handoff_pending(self) -> bool:
        nxt = self.applied_epoch + 1
        return any(p.change.new_epoch == nxt for p in self._pending.values())

    def handoff_blocks(self, round_: Round) -> bool:
        """True when the certification wall covers `round_`: a pending
        handoff declared its boundary at or below it, so this node must
        not help certify the round until the carrier commits."""
        boundary = self.handoff_boundary()
        return boundary is not None and round_ >= boundary

    async def note_pending(
        self, change: EpochChange, carrier_round: Round, store=None
    ) -> bool:
        """Record an admitted carrier (called from the proposal path once
        `validate` passed). Idempotent per (change, carrier round).
        Persisted so a node crashing between admission and commit wakes
        up with the wall intact — the boundary-crash scenarios pin it."""
        if change.new_epoch <= self.applied_epoch:
            return False
        key = change.digest().data
        entry = self._pending.get(key)
        if entry is None:
            entry = self._pending[key] = _PendingHandoff(change)
        if carrier_round in entry.carriers:
            return False
        entry.carriers.add(carrier_round)
        log.info(
            "Epoch handoff pending: %s carried by B%s (wall at round %s)",
            change,
            carrier_round,
            change.activation_round,
        )
        if store is not None:
            await self.save(store)
        return True

    def note_hold(self, round_: Round, kind: str) -> None:
        """Account one walled certification act (vote or proposal)."""
        _M_HANDOFF_HOLDS.inc()
        log.warning(
            "epoch handoff wall: withholding %s at round %s (boundary %s "
            "awaits the carrier's commit)",
            kind,
            round_,
            self.handoff_boundary(),
        )

    async def note_commit(self, committed_round: Round, store=None) -> None:
        """Drop pendings whose every observed carrier the committed chain
        has passed WITHOUT applying: commits walk ancestors, so a carrier
        at or below the committed floor that did not apply is not in the
        committed chain — a dead fork whose boundary must not wall
        liveness. Applied changes were already cleared by `apply`."""
        dropped = False
        for key, p in list(self._pending.items()):
            if p.change.new_epoch <= self.applied_epoch:
                del self._pending[key]
                dropped = True
                continue
            if p.carriers and all(r <= committed_round for r in p.carriers):
                del self._pending[key]
                dropped = True
                _M_HANDOFF_ABANDONED.inc()
                log.info(
                    "Epoch handoff abandoned: %s — the chain committed past "
                    "every carrier without it (fork died)",
                    p.change,
                )
        if dropped and store is not None:
            await self.save(store)

    # -- validation (proposal ingress) --------------------------------------

    def validate(self, change: EpochChange, block_round: Round) -> None:
        """Structural admission for an EpochChange riding a round-
        `block_round` proposal; raises ReconfigError. The author's
        signature is checked separately in Block.verify_async (it rides
        the block's batched service group)."""
        try:
            ensure(
                change.new_epoch == self.epoch_for_round(block_round) + 1,
                ReconfigError(
                    f"epoch change to {change.new_epoch} out of sequence "
                    f"(round {block_round} is epoch "
                    f"{self.epoch_for_round(block_round)})"
                ),
            )
            ensure(
                change.activation_round >= block_round + MIN_ACTIVATION_MARGIN,
                ReconfigError(
                    f"activation round {change.activation_round} inside the "
                    f"commit margin of round {block_round}"
                ),
            )
            ensure(
                len(change.members) > 0,
                ReconfigError("epoch change with an empty committee"),
            )
            ensure(
                len(change.members) <= MAX_WIRE_MEMBERS,
                ReconfigError(
                    f"epoch change with {len(change.members)} members "
                    f"(cap {MAX_WIRE_MEMBERS})"
                ),
            )
            committee = change.committee()
            ensure(
                committee.total_votes() > 0,
                ReconfigError("epoch change with zero total stake"),
            )
        except ReconfigError:
            _M_REJECTED.inc()
            raise

    # -- apply-on-commit -----------------------------------------------------

    async def apply(
        self, change: EpochChange, store=None, trigger_round: Round | None = None
    ) -> bool:
        """Epoch-commit rule: called only once the carrying block is
        2-chain committed. Idempotent (a change committed in two blocks,
        or re-applied from persistence, is a no-op the second time).

        The boundary is ALWAYS the DECLARED activation round — pure
        chain content, so every node (live, restarting, or replaying a
        range-synced chain) derives the identical round -> committee
        map. The block that locally completes the carrier's 2-chain is
        deliberately NOT folded in: two nodes can first see different
        QC-carrying envelopes (one of which may never certify), so any
        trigger-derived boundary would diverge across honest nodes — a
        schedule split, the one thing the epoch-commit rule exists to
        prevent.

        Under the epoch-final handoff the commit CANNOT land past the
        boundary on an honest chain: the wall (handoff_blocks) keeps the
        old committee from certifying gap rounds, so `trigger_round >=
        activation_round` — once a counted-but-tolerated margin
        pathology — is now a hard invariant violation (it requires a
        Byzantine quorum or a broken wall), logged at error level,
        counted in `reconfig.late_applies`, and escalated through the
        AnomalyWatchdog (`handoff_violation` auto-dump). The chaos
        SafetyChecker derives the same contract independently from chain
        content."""
        committee = change.committee()
        if not self.schedule.apply(change.activation_round, committee):
            return False
        self._pending.pop(change.digest().data, None)
        self._mempool_addrs.update(change.mempool_addresses())
        if trigger_round is not None:
            lag = max(0, trigger_round - (change.activation_round - 1))
            _M_HANDOFF_LAG.record(float(lag))
            if lag > 0:
                _M_LATE_APPLIES.inc()
                # WARNING level (not ERROR): the benchmark LogParser
                # treats ERROR lines as a crashed run and aborts parsing;
                # the severity escalation rides the watchdog trigger +
                # the scraped "VIOLATION" marker instead.
                log.warning(
                    "Epoch handoff VIOLATION: epoch %s commit landed at "
                    "round %s, at/past the declared activation round %s — "
                    "gap rounds were certified by the old committee (the "
                    "epoch-final wall should have made this impossible)",
                    committee.epoch,
                    trigger_round,
                    change.activation_round,
                )
                tracing.WATCHDOG.note_handoff_violation(
                    committee.epoch, change.activation_round, trigger_round
                )
            else:
                # NOTE: parsed by the benchmark LogParser (+ RECONFIG:).
                log.info(
                    "Epoch handoff to %s committed at round %s (boundary "
                    "%s, slack %s rounds)",
                    committee.epoch,
                    trigger_round,
                    change.activation_round,
                    change.activation_round - trigger_round,
                )
        self._switched(committee, change.activation_round)
        if store is not None:
            await self.save(store)
        return True

    def _switched(self, committee: Committee, activation_round: Round) -> None:
        _M_SWITCHES.inc()
        _M_EPOCH.set(committee.epoch)
        # NOTE: this log entry is parsed by the benchmark LogParser.
        log.info(
            "Epoch switch to %s at activation round %s (%s validators, quorum %s)",
            committee.epoch,
            activation_round,
            committee.size(),
            committee.quorum_threshold(),
        )
        self._reregister(committee)
        for hook in self._on_switch:
            hook(committee, activation_round)

    def _reregister(self, committee: Committee) -> None:
        """Device-side committee succession: swap the backend's resident
        key tables to the new epoch. TpuBackend registration is snapshot-
        pinned (ops/ed25519.CommitteeTable): batches staged against the
        old table finish on the OLD epoch's replicas while new traffic
        resolves against the new indices — no flush barrier needed."""
        if not self._register_backend:
            return
        from ..crypto import get_backend

        backend = get_backend()
        if hasattr(backend, "register_committee"):
            try:
                backend.register_committee(committee.sorted_keys())
            except Exception as e:  # registration is an optimization only
                log.warning("epoch committee re-registration failed: %r", e)

    # -- persistence ---------------------------------------------------------

    async def save(self, store) -> None:
        """Persist applied boundaries AND pending handoffs. The pending
        half is what survives a crash landing exactly at the activation
        boundary: the restarted node must wake with the wall intact, or
        it could certify gap rounds its crashed incarnation refused."""
        entries = []
        for r, c in self.schedule.entries()[1:]:  # genesis comes from config
            entry = {"activation_round": r, "committee": c.to_json()}
            maddrs = {
                pk.encode_base64(): f"{a[0]}:{a[1]}"
                for pk in c.sorted_keys()
                for a in (self._mempool_addrs.get(pk),)
                if a is not None
            }
            if maddrs:
                entry["mempool_addresses"] = maddrs
            entries.append(entry)
        pending = [
            {
                "change": _encode_change_hex(p.change),
                "carriers": sorted(p.carriers),
            }
            for _key, p in sorted(self._pending.items())
        ]
        state = {"entries": entries, "pending": pending}
        await store.write(_STORE_KEY, json.dumps(state).encode())

    async def load(self, store) -> None:
        """Rebuild applied boundaries and pending handoffs after a
        restart (idempotent). The switch hooks re-fire so the backend
        tables match the persisted epoch before the node rejoins; the
        restored pendings re-arm the certification wall, so a node that
        crashed mid-handoff can never re-judge (or help re-certify) gap
        rounds its pre-crash incarnation walled off."""
        raw = await store.read(_STORE_KEY)
        if raw is None:
            return
        state = json.loads(raw.decode())
        if isinstance(state, list):  # pre-handoff format: entries only
            entries, pending = state, []
        else:
            entries = state.get("entries", [])
            pending = state.get("pending", [])
        for entry in entries:
            committee = Committee.from_json(entry["committee"])
            if self.schedule.apply(entry["activation_round"], committee):
                for pk_b64, addr in entry.get("mempool_addresses", {}).items():
                    host, port = addr.rsplit(":", 1)
                    self._mempool_addrs[PublicKey.decode_base64(pk_b64)] = (
                        host,
                        int(port),
                    )
                self._switched(committee, entry["activation_round"])
        for p in pending:
            change = _decode_change_hex(p["change"])
            if change.new_epoch <= self.applied_epoch:
                continue
            entry = self._pending.setdefault(
                change.digest().data, _PendingHandoff(change)
            )
            entry.carriers.update(p["carriers"])


def _encode_change_hex(change: EpochChange) -> str:
    w = Writer()
    change.encode(w)
    return w.bytes().hex()


def _decode_change_hex(data: str) -> EpochChange:
    return EpochChange.decode(Reader(bytes.fromhex(data)))
