"""Consensus error types (reference consensus/src/error.rs:24-65)."""

from __future__ import annotations


class ConsensusError(Exception):
    pass


class InvalidSignatureError(ConsensusError):
    pass


class WrongLeaderError(ConsensusError):
    def __init__(self, block_round: int, author, leader) -> None:
        super().__init__(
            f"wrong leader for round {block_round}: got {author}, expected {leader}"
        )


class AuthorityReuseError(ConsensusError):
    def __init__(self, name) -> None:
        super().__init__(f"authority {name} appears twice in certificate")


class UnknownAuthorityError(ConsensusError):
    def __init__(self, name) -> None:
        super().__init__(f"unknown authority {name}")


class QCRequiresQuorumError(ConsensusError):
    pass


class TCRequiresQuorumError(ConsensusError):
    pass


class MalformedBlockError(ConsensusError):
    pass


class ReconfigError(ConsensusError):
    """An EpochChange that violates the epoch-commit rule's admission
    checks (sequence, activation margin, empty successor set)."""


def ensure(cond: bool, err: ConsensusError) -> None:
    """The reference's ensure! macro (consensus/src/error.rs)."""
    if not cond:
        raise err
