from .config import Committee, NodeParameters, Secret
from .node import Node

__all__ = ["Committee", "NodeParameters", "Secret", "Node"]
