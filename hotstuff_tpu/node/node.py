"""Node composition root (reference node/src/node.rs:34-99): reads configs,
builds the store and signing actor, wires the cross-subsystem channels, and
boots Mempool then Consensus. `analyze_block` drains the commit channel (the
application layer stub the reference also has, node/src/node.rs:95-99).
"""

from __future__ import annotations

import asyncio
import logging

from ..consensus import Consensus
from ..crypto import SignatureService
from ..mempool import Mempool
from ..store import Store
from ..utils.actors import channel
from .config import Committee, NodeParameters, Secret

log = logging.getLogger("hotstuff.node")


class Node:
    def __init__(
        self,
        committee_path: str,
        key_path: str,
        store_path: str,
        parameters_path: str | None = None,
    ) -> None:
        self.committee = Committee.read(committee_path)
        self.secret = Secret.read(key_path)
        self.parameters = (
            NodeParameters.read(parameters_path)
            if parameters_path
            else NodeParameters.default()
        )
        self.store_path = store_path
        self.commit_channel = channel()
        # Set by boot(): the node's shared BatchVerificationService. The
        # telemetry plane (node run --telemetry-port) reads its LaneStats
        # for the per-lane SLO evaluation.
        self.verification_service = None
        # The node's epoch view (consensus/reconfig.py): committed
        # committee changes apply here, re-registering the device-resident
        # committee tables at every switch (register_backend=True).
        from ..consensus.reconfig import EpochManager

        self.epoch_manager = EpochManager(self.committee.consensus)

    def boot(self) -> None:
        """Must run inside an event loop (actors spawn on construction)."""
        name = self.secret.name
        self.register_committee()
        store = Store(self.store_path)
        signature_service = SignatureService(self.secret.secret)
        # One verification service per node: consensus QC/TC/vote checks and
        # mempool payload/synthetic batches coalesce into shared backend
        # dispatches (the async seam of crypto/src/lib.rs:226-252 generalised
        # to verification).
        from ..crypto.batch_service import BatchVerificationService

        verification_service = BatchVerificationService()
        self.verification_service = verification_service
        consensus_mempool_channel = channel()
        consensus_core_channel = channel()

        # Commit-proof serving plane (§5.5q): one registry shared by the
        # ingress pipeline (admitted-tx feed), the payload maker (flush
        # pairing) and the consensus core (commit feed). The persisted
        # newest window reloads in the background — queries racing the
        # load just see PENDING/UNKNOWN until their proofs reappear.
        self.proof_registry = None
        if self.parameters.mempool.ingress_enabled:
            from ..proofs.registry import ProofRegistry
            from ..utils.actors import spawn

            self.proof_registry = ProofRegistry(store=store)
            spawn(self.proof_registry.load(), name="proof-registry-load")

        Mempool.run(
            name,
            self.committee.mempool,
            self.parameters.mempool,
            store,
            signature_service,
            consensus_mempool_channel,
            consensus_core_channel,
            verification_service=verification_service,
            # The SAME epoch view consensus applies committed changes to:
            # payload gossip fan-out, sync and address resolution cross
            # an epoch boundary at the same activation round (§5.5j).
            epoch_manager=self.epoch_manager,
            proof_registry=self.proof_registry,
        )
        Consensus.run(
            name,
            self.committee.consensus,
            self.parameters.consensus,
            store,
            signature_service,
            consensus_mempool_channel,
            self.commit_channel,
            core_channel=consensus_core_channel,
            verification_service=verification_service,
            epoch_manager=self.epoch_manager,
            proof_registry=self.proof_registry,
        )
        log.info("Node %s successfully booted", name.short())

    def register_committee(self, warmup: bool = False) -> None:
        """Install the consensus committee's validator keys as device-
        resident verification precompute on the active crypto backend
        (TpuBackend.register_committee). Idempotent; call again after an
        epoch reconfiguration — a changed key set rebuilds the table.
        With `warmup`, the committee kernel is compiled at every dispatch
        bucket width before returning (do this before joining consensus)."""
        from ..crypto import get_backend

        backend = get_backend()
        if hasattr(backend, "register_committee"):
            backend.register_committee(
                self.committee.consensus.sorted_keys(), warmup=warmup
            )

    async def analyze_block(self) -> None:
        """Application layer: drain committed blocks (node/src/node.rs:95-99)."""
        while True:
            _block = await self.commit_channel.get()
            # Here the application would execute the ordered transactions.
