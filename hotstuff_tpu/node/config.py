"""Node configuration files (reference node/src/config.rs:13-78).

The Export pattern: every config is a JSON file with read/write helpers.
  * Secret    -- {name, secret} keypair file (written by `node keys`)
  * Committee -- {consensus: {...}, mempool: {...}} addresses + stakes
  * NodeParameters -- {consensus: {...}, mempool: {...}} tuning knobs
"""

from __future__ import annotations

import json
from dataclasses import dataclass

from ..consensus.config import Committee as ConsensusCommittee
from ..consensus.config import Parameters as ConsensusParameters
from ..crypto import PublicKey, SecretKey, generate_production_keypair
from ..mempool.config import MempoolCommittee, MempoolParameters


class ConfigError(Exception):
    pass


def _read_json(path: str) -> dict:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise ConfigError(f"failed to read config {path}: {e}") from e


def _write_json(path: str, obj: dict) -> None:
    with open(path, "w") as f:
        json.dump(obj, f, indent=2, sort_keys=True)
        f.write("\n")


@dataclass(slots=True)
class Secret:
    """Keypair file (node/src/config.rs:41-57)."""

    name: PublicKey
    secret: SecretKey

    @staticmethod
    def new() -> "Secret":
        pk, sk = generate_production_keypair()
        return Secret(pk, sk)

    @staticmethod
    def read(path: str) -> "Secret":
        obj = _read_json(path)
        return Secret(
            PublicKey.decode_base64(obj["name"]),
            SecretKey.decode_base64(obj["secret"]),
        )

    def write(self, path: str) -> None:
        _write_json(
            path,
            {"name": self.name.encode_base64(), "secret": self.secret.encode_base64()},
        )


@dataclass(slots=True)
class Committee:
    """Combined consensus+mempool committee (node/src/config.rs:59-68)."""

    consensus: ConsensusCommittee
    mempool: MempoolCommittee

    @staticmethod
    def read(path: str) -> "Committee":
        obj = _read_json(path)
        return Committee(
            ConsensusCommittee.from_json(obj["consensus"]),
            MempoolCommittee.from_json(obj["mempool"]),
        )

    def write(self, path: str) -> None:
        _write_json(
            path,
            {"consensus": self.consensus.to_json(), "mempool": self.mempool.to_json()},
        )


@dataclass(slots=True)
class NodeParameters:
    """Combined parameters (node/src/config.rs:70-78)."""

    consensus: ConsensusParameters
    mempool: MempoolParameters

    @staticmethod
    def default() -> "NodeParameters":
        return NodeParameters(ConsensusParameters(), MempoolParameters())

    @staticmethod
    def read(path: str) -> "NodeParameters":
        obj = _read_json(path)
        return NodeParameters(
            ConsensusParameters.from_json(obj.get("consensus", {})),
            MempoolParameters.from_json(obj.get("mempool", {})),
        )

    def write(self, path: str) -> None:
        _write_json(
            path,
            {"consensus": self.consensus.to_json(), "mempool": self.mempool.to_json()},
        )
