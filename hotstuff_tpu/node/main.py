"""The `node` binary (reference node/src/main.rs:16-92).

Subcommands:
  * keys --filename F                      -- generate a keypair file
  * run --keys K --committee C --store S [--parameters P] [--crypto cpu|tpu]
  * deploy --nodes N                       -- in-process local testbed on
    ports 7000/7100/7200 (node/src/main.rs:94-153)

The --crypto flag selects the CryptoBackend (the BASELINE `fab ...
--crypto=...` requirement): `cpu` (OpenSSL ed25519 baseline) or `tpu`
(vmapped JAX batch verification).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import sys

from ..utils.logging import setup_logging


def _cmd_keys(args) -> None:
    from .config import Secret

    Secret.new().write(args.filename)
    print(f"Wrote keypair to {args.filename}")


async def _run_node(args) -> None:
    from .node import Node

    backend = None
    if args.crypto != "cpu":
        from ..crypto.backend import make_backend, set_backend

        kwargs = {}
        if args.crypto == "remote":
            host, port = args.crypto_addr.rsplit(":", 1)
            kwargs["addr"] = (host, int(port))
            kwargs["crossover"] = args.crypto_crossover
        if args.crypto == "tpu" and args.crypto_sharded:
            # Multi-chip: shard verification batches over every attached
            # device. Committee registration below pushes one replicated
            # table copy per chip (parallel/mesh.py).
            kwargs["sharded"] = True
        backend = make_backend(args.crypto, **kwargs)
        set_backend(backend)  # returns the PREVIOUS backend — don't chain
        if not args.no_warmup:
            # Compile every device bucket BEFORE the pacemaker can arm:
            # lazy first-dispatch compilation (tens of seconds) otherwise
            # stalls early rounds past timeout_delay (see
            # TpuBackend.warmup). Runs before boot(), so nothing is stalled.
            from ..crypto.remote import warmup_backend

            warmup_backend(backend)
    node = Node(args.committee, args.keys, args.store, args.parameters)
    if args.ingress:
        # CLI override on top of the parameters file: boot the
        # authenticated client ingress (front port + ingress_port_offset).
        node.parameters.mempool.ingress_enabled = True
    # Committee registration at startup: validator keys become device-
    # resident verification precompute (decompression + window tables paid
    # once, not per batch), with the committee kernel compiled before the
    # node joins consensus. boot() re-asserts the registration (a no-op
    # for an unchanged key set); re-run node.register_committee on epoch
    # reconfiguration — a changed key set rebuilds the table.
    if backend is not None:
        node.register_committee(warmup=not args.no_warmup)
    node.boot()
    if args.telemetry_port is not None:
        # Live telemetry plane + framed-JSON scrape endpoint
        # (utils/telemetry.py): periodic delta snapshots over the metrics
        # registry, per-lane SLO burn evaluation against the node's
        # LaneStats, and the device-occupancy timeline summary — polled
        # by tools/telemetry_dash.py. The watchdog attach means every
        # --trace-out auto-dump embeds the last K snapshots.
        import os as _os

        from ..network import net as _net
        from ..ops import timeline
        from ..utils import telemetry
        from ..utils.actors import spawn

        plane = telemetry.TelemetryPlane(
            label=_os.path.splitext(_os.path.basename(args.keys))[0],
            lane_stats=node.verification_service.lane_stats,
            timeline_fn=timeline.summary,
            # Per-peer link/RTT ledger (network observatory): a process
            # has one node label, so the default-vantage snapshot is
            # exactly this node's directed links.
            peers_fn=_net.peer_snapshot,
        )
        plane.attach_watchdog()
        server = telemetry.TelemetryServer(
            ("0.0.0.0", args.telemetry_port), plane
        )
        server.launch()
        spawn(plane.run(), name="telemetry-plane")
    await node.analyze_block()


async def _deploy_testbed(args) -> None:
    """In-process local testbed (node/src/main.rs:94-153): N nodes on
    localhost ports consensus 7000+i, mempool 7100+i, front 7200+i."""
    import random

    from ..consensus.config import Committee as CCommittee
    from ..consensus.config import Parameters as CParameters
    from ..crypto import SignatureService, generate_keypair
    from ..mempool.config import MempoolCommittee, MempoolParameters
    from ..mempool import Mempool
    from ..consensus import Consensus
    from ..store import Store
    from ..utils.actors import channel, spawn

    n = args.nodes
    rng = random.Random(0)
    keys = [generate_keypair(rng) for _ in range(n)]
    consensus_committee = CCommittee.new(
        [(pk, 1, ("127.0.0.1", 7000 + i)) for i, (pk, _) in enumerate(keys)]
    )
    mempool_committee = MempoolCommittee.new(
        [
            (pk, ("127.0.0.1", 7200 + i), ("127.0.0.1", 7100 + i))
            for i, (pk, _) in enumerate(keys)
        ]
    )
    nodes = []
    for i, (pk, sk) in enumerate(keys):
        store = Store(f".db_{i}/log")
        sig = SignatureService(sk)
        cm_channel = channel()
        core_channel = channel()
        commit_channel = channel()
        Mempool.run(
            pk, mempool_committee, MempoolParameters(), store, sig, cm_channel, core_channel
        )
        Consensus.run(
            pk,
            consensus_committee,
            CParameters(),
            store,
            sig,
            cm_channel,
            commit_channel,
            core_channel=core_channel,
        )
        nodes.append(commit_channel)

    async def drain(ch):
        while True:
            await ch.get()

    await asyncio.gather(*(drain(c) for c in nodes))


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(prog="node", description=__doc__)
    parser.add_argument("-v", "--verbose", action="count", default=2)
    sub = parser.add_subparsers(dest="command", required=True)

    p_keys = sub.add_parser("keys", help="generate a keypair file")
    p_keys.add_argument("--filename", required=True)

    p_run = sub.add_parser("run", help="run a node")
    p_run.add_argument("--keys", required=True)
    p_run.add_argument("--committee", required=True)
    p_run.add_argument("--parameters", default=None)
    p_run.add_argument("--store", required=True)
    p_run.add_argument(
        "--crypto", default="cpu", choices=["cpu", "tpu", "remote"]
    )
    p_run.add_argument(
        "--crypto-addr",
        default="127.0.0.1:9700",
        help="sidecar address for --crypto remote (host:port)",
    )
    p_run.add_argument(
        "--crypto-crossover",
        type=int,
        default=64,
        help="batches below this size verify on the local CPU",
    )
    p_run.add_argument(
        "--crypto-sharded",
        action="store_true",
        help="with --crypto tpu: shard verification over every attached "
        "device (ShardedEd25519Verifier); committee registration then "
        "replicates the validator tables onto every chip",
    )
    p_run.add_argument(
        "--ingress",
        action="store_true",
        help="serve the authenticated client ingress (signed transactions, "
        "admission control with fee/priority lanes, retry-after "
        "backpressure) on front_port + mempool ingress_port_offset; "
        "equivalent to ingress_enabled in the mempool parameters",
    )
    p_run.add_argument(
        "--no-warmup",
        action="store_true",
        help="skip pre-compiling device kernels before joining consensus",
    )
    p_run.add_argument(
        "--telemetry-port",
        type=int,
        default=None,
        metavar="PORT",
        help="serve the live telemetry scrape endpoint (framed JSON "
        "request/response on the stack's 4-byte framing): periodic "
        "metric delta snapshots, SLO burn-rate alerts, lane queueing, "
        "and the device-occupancy timeline. Poll with "
        "tools/telemetry_dash.py --poll host:PORT",
    )
    p_run.add_argument(
        "--metrics-out",
        default=None,
        help="write the structured metrics dump (utils/metrics.py) to this "
        "path on exit/SIGTERM",
    )
    p_run.add_argument(
        "--trace-out",
        default=None,
        help="write the flight-recorder dump (utils/tracing.py) to this "
        "path on exit/SIGTERM; anomaly-watchdog dumps land next to it as "
        "<path>.watchdog-<reason>-<n>.json. HOTSTUFF_TRACE=0 disables "
        "recording, HOTSTUFF_TRACE_RING sizes the ring",
    )

    p_deploy = sub.add_parser("deploy", help="in-process local testbed")
    p_deploy.add_argument("--nodes", type=int, required=True)

    args = parser.parse_args(argv)
    if (
        args.command == "run"
        and args.crypto_sharded
        and args.crypto != "tpu"
    ):
        # A run that silently ignored the flag would record numbers under
        # a different config than the operator specified (same convention
        # as the sidecar's --multihost/--chunk guards).
        parser.error("--crypto-sharded requires --crypto tpu")
    setup_logging(args.verbose)

    # GIL switch interval: the saturated-node profile (data/profiles/)
    # shows ~5 ms stalls on every to_thread crypto dispatch — the default
    # sys.setswitchinterval(0.005) convoy between the event loop and the
    # verification worker threads. A shorter interval cuts the handoff
    # latency on single-core hosts.
    import os

    try:
        sys.setswitchinterval(
            float(os.environ.get("HOTSTUFF_SWITCH_INTERVAL", "0.001"))
        )
    except ValueError:
        logging.getLogger("hotstuff.node").warning(
            "ignoring malformed HOTSTUFF_SWITCH_INTERVAL"
        )

    # Exit-time flushers, shared by the profiler and --metrics-out: the
    # benchmark harness stops nodes with SIGTERM, which skips atexit by
    # default, so both hooks ride one SIGTERM handler + one atexit.
    flushers = []
    if args.command == "run":
        from ..utils import metrics

        # Periodic `METRICS {json}` snapshot line on hotstuff.metrics
        # (scraped by benchmark.logs.LogParser); <= 0 disables.
        try:
            interval = float(os.environ.get("HOTSTUFF_METRICS_INTERVAL", "5"))
        except ValueError:
            logging.getLogger("hotstuff.metrics").warning(
                "ignoring malformed HOTSTUFF_METRICS_INTERVAL"
            )
            interval = 5.0
        metrics.start_periodic_emitter(interval)
        if args.metrics_out:

            def _write_metrics():
                try:
                    metrics.write_json(args.metrics_out)
                except OSError as e:
                    logging.getLogger("hotstuff.metrics").warning(
                        "failed to write metrics dump: %r", e
                    )

            flushers.append(_write_metrics)
        if args.trace_out:
            from ..utils import tracing

            # Label this process's events with the keys-file stem so
            # multi-node dumps stitch with stable node names, and arm the
            # anomaly watchdog's auto-dump next to the exit dump.
            tracing.NODE_LABEL.set(os.path.splitext(
                os.path.basename(args.keys)
            )[0])
            tracing.WATCHDOG.set_auto_dump(args.trace_out)

            def _write_trace():
                try:
                    tracing.write_json(args.trace_out)
                except OSError as e:
                    logging.getLogger("hotstuff.tracing").warning(
                        "failed to write trace dump: %r", e
                    )

            flushers.append(_write_trace)

    # HOTSTUFF_PROFILE=<path>: run the node under cProfile and dump stats
    # to <path>.<pid> on SIGTERM/exit (SURVEY §5.5 observability; used by
    # the protocol-plane ceiling analysis in data/profiles/).
    if args.command == "run" and os.environ.get("HOTSTUFF_PROFILE"):
        import cProfile

        profile_path = f"{os.environ['HOTSTUFF_PROFILE']}.{os.getpid()}"
        profiler = cProfile.Profile()
        profiler.enable()

        def _dump_profile():
            profiler.disable()
            profiler.dump_stats(profile_path)

        flushers.append(_dump_profile)

    if args.command == "run":
        # Drain every live DispatchPipeline's workers on SIGTERM too —
        # the handler below exits via os._exit, which skips the
        # pipeline's own atexit hook (ops/pipeline.py close_all).
        from ..ops.pipeline import close_all as _drain_pipelines

        flushers.append(_drain_pipelines)

    if flushers:
        import atexit
        import signal

        def _flush_all():
            for flush in flushers:
                flush()

        def _on_term(*_a):
            _flush_all()
            os._exit(0)

        signal.signal(signal.SIGTERM, _on_term)
        atexit.register(_flush_all)

    if args.command == "keys":
        _cmd_keys(args)
    elif args.command == "run":
        asyncio.run(_run_node(args))
    elif args.command == "deploy":
        asyncio.run(_deploy_testbed(args))


if __name__ == "__main__":
    main()
