"""Benchmark load generator (reference node/src/client.rs:86-167).

Sends `--rate` transactions/sec of `--size` bytes to a node's front port in
bursts on a 50 ms tick. The FIRST transaction of each burst is a "sample":
a zero byte, a big-endian u64 counter, then zero padding -- the LogParser
joins sample ids to payload digests to commit timestamps for end-to-end
latency. Other transactions start with 0x01 followed by random bytes.
Before sending, waits until all `--nodes` addresses are TCP-reachable.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import struct
import time

from ..network.net import frame
from ..utils.logging import setup_logging

log = logging.getLogger("hotstuff.client")

BURST_INTERVAL = 0.05  # 50 ms ticks (client.rs:115)


async def wait_for_nodes(addresses: list[tuple[str, int]]) -> None:
    """Block until every node's consensus port accepts connections
    (client.rs:96-112)."""
    for host, port in addresses:
        while True:
            try:
                _, w = await asyncio.open_connection(host, port)
                w.close()
                break
            except OSError:
                await asyncio.sleep(0.1)


async def run_client(
    target: tuple[str, int],
    size: int,
    rate: int,
    nodes: list[tuple[str, int]],
    duration: float | None = None,
) -> None:
    if size < 9:
        raise ValueError("transaction size must be at least 9 bytes")
    # NOTE: these log entries are used to compute performance.
    log.info("Transactions size: %s B", size)
    log.info("Transactions rate: %s tx/s", rate)
    if nodes:
        log.info("Waiting for all nodes to be online...")
        await wait_for_nodes(nodes)

    reader, writer = await asyncio.open_connection(target[0], target[1])
    burst = max(1, int(rate * BURST_INTERVAL))
    counter = 0
    rnd = os.urandom(size - 9)
    # Monotonic per-tx tag so every client (and every burst) sends distinct
    # transactions — payload digests must not collide (client.rs:130).
    tx_tag = int.from_bytes(os.urandom(8), "big")
    log.info("Start sending transactions")
    start = time.monotonic()
    next_tick = start
    while duration is None or (time.monotonic() - start) < duration:
        t0 = time.monotonic()
        for x in range(burst):
            if x == 0:
                # Sample transaction: 0x00 + u64 counter + padding.
                tx = b"\x00" + struct.pack(">Q", counter) + bytes(size - 9)
                # NOTE: This log entry is used to compute performance.
                log.info("Sending sample transaction %s", counter)
            else:
                tx_tag = (tx_tag + 1) & 0xFFFFFFFFFFFFFFFF
                tx = b"\x01" + struct.pack(">Q", tx_tag) + rnd
            writer.write(frame(tx))
        await writer.drain()
        counter += 1
        next_tick += BURST_INTERVAL
        now = time.monotonic()
        if now > next_tick:
            log.warning("rate too high for this client")
            next_tick = now
        else:
            await asyncio.sleep(next_tick - now)


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(prog="client", description=__doc__)
    parser.add_argument("-v", "--verbose", action="count", default=2)
    parser.add_argument("target", help="front address host:port of the target node")
    parser.add_argument("--size", type=int, required=True, help="tx size in bytes")
    parser.add_argument("--rate", type=int, required=True, help="tx per second")
    parser.add_argument(
        "--nodes",
        nargs="*",
        default=[],
        help="consensus addresses to wait for before sending",
    )
    parser.add_argument("--duration", type=float, default=None, help="seconds to run")
    args = parser.parse_args(argv)
    if args.size < 9:
        parser.error("--size must be at least 9 bytes (sample tx header)")
    setup_logging(args.verbose)

    def parse(s: str) -> tuple[str, int]:
        host, port = s.rsplit(":", 1)
        return (host, int(port))

    asyncio.run(
        run_client(
            parse(args.target),
            args.size,
            args.rate,
            [parse(n) for n in args.nodes],
            args.duration,
        )
    )


if __name__ == "__main__":
    main()
