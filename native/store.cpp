// Native log-structured KV engine for the Store actor.
//
// The reference's store crate wraps rocksdb behind a single-writer actor
// (store/src/lib.rs:15-92). Here the data plane — hash index, append-only
// length-prefixed log, crash-safe replay that ignores a torn tail — is
// C++; the Python actor (hotstuff_tpu/store/store.py) keeps the channel
// protocol and notify_read obligations and calls in via ctypes.
//
// Log record: <u32 klen><u32 vlen><key><value>, little-endian.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

#include <unistd.h>

namespace {

struct Store {
  std::unordered_map<std::string, std::string> index;
  FILE *log = nullptr;
  std::string path;
  bool fsync_writes = false;
};

// Replays the log into the index and returns the byte offset of the last
// complete record, so the caller can truncate a torn tail before appending
// (appending after torn bytes would make every later record unreachable on
// the next replay).
long replay(Store *s, const char *path) {
  FILE *f = fopen(path, "rb");
  if (!f) return 0;
  std::vector<uint8_t> hdr(8);
  std::string key, val;
  long good = 0;
  for (;;) {
    if (fread(hdr.data(), 1, 8, f) != 8) break;
    uint32_t klen, vlen;
    memcpy(&klen, hdr.data(), 4);
    memcpy(&vlen, hdr.data() + 4, 4);
    // guard against a corrupt header at the torn tail
    if (klen > (1u << 20) || vlen > (1u << 28)) break;
    key.resize(klen);
    val.resize(vlen);
    if (klen && fread(&key[0], 1, klen, f) != klen) break;
    if (vlen && fread(&val[0], 1, vlen, f) != vlen) break;
    s->index[key] = val;
    good = ftell(f);
  }
  fclose(f);
  return good;
}

bool write_record(FILE *f, const std::string &k, const std::string &v) {
  uint32_t kl = (uint32_t)k.size(), vl = (uint32_t)v.size();
  if (fwrite(&kl, 4, 1, f) != 1) return false;
  if (fwrite(&vl, 4, 1, f) != 1) return false;
  if (kl && fwrite(k.data(), 1, kl, f) != kl) return false;
  if (vl && fwrite(v.data(), 1, vl, f) != vl) return false;
  return true;
}

}  // namespace

extern "C" {

void *hs_store_open(const char *path, int fsync_writes) {
  auto *s = new Store;
  s->fsync_writes = fsync_writes != 0;
  if (path && path[0]) {
    s->path = path;
    long good = replay(s, path);
    if (truncate(path, good) != 0 && good > 0) {
      // fall through: append still works, replay will re-stop at `good`
    }
    s->log = fopen(path, "ab");
    if (!s->log) {
      delete s;
      return nullptr;
    }
  }
  return s;
}

// Rewrites the log with live keys only (dead versions dropped), atomically
// via rename. Returns new log size in bytes, or -1 on failure. The role
// rocksdb's background compaction plays in the reference (store/src/lib.rs).
int64_t hs_store_compact(void *sp) {
  auto *s = static_cast<Store *>(sp);
  if (!s->log) return 0;
  std::string tmp = s->path + ".compact";
  FILE *out = fopen(tmp.c_str(), "wb");
  if (!out) return -1;
  for (const auto &kv : s->index) {
    if (!write_record(out, kv.first, kv.second)) {
      fclose(out);
      remove(tmp.c_str());
      return -1;
    }
  }
  if (fflush(out) != 0 || fsync(fileno(out)) != 0) {
    fclose(out);
    remove(tmp.c_str());
    return -1;
  }
  fclose(out);
  fclose(s->log);
  if (rename(tmp.c_str(), s->path.c_str()) != 0) {
    s->log = fopen(s->path.c_str(), "ab");
    return -1;
  }
  s->log = fopen(s->path.c_str(), "ab");
  if (!s->log) return -1;
  long sz = ftell(s->log);
  return (int64_t)sz;
}

int hs_store_write(void *sp, const uint8_t *k, int64_t klen, const uint8_t *v,
                   int64_t vlen) {
  auto *s = static_cast<Store *>(sp);
  s->index[std::string((const char *)k, klen)] =
      std::string((const char *)v, vlen);
  if (s->log) {
    uint32_t kl = (uint32_t)klen, vl = (uint32_t)vlen;
    if (fwrite(&kl, 4, 1, s->log) != 1) return -1;
    if (fwrite(&vl, 4, 1, s->log) != 1) return -1;
    if (klen && fwrite(k, 1, klen, s->log) != (size_t)klen) return -1;
    if (vlen && fwrite(v, 1, vlen, s->log) != (size_t)vlen) return -1;
    if (fflush(s->log) != 0) return -1;
  }
  return 0;
}

// Returns value length and malloc'd buffer in *out (caller frees via
// hs_free), or -1 if absent.
int64_t hs_store_read(void *sp, const uint8_t *k, int64_t klen,
                      uint8_t **out) {
  auto *s = static_cast<Store *>(sp);
  auto it = s->index.find(std::string((const char *)k, klen));
  if (it == s->index.end()) return -1;
  *out = (uint8_t *)malloc(it->second.size());
  memcpy(*out, it->second.data(), it->second.size());
  return (int64_t)it->second.size();
}

int hs_store_contains(void *sp, const uint8_t *k, int64_t klen) {
  auto *s = static_cast<Store *>(sp);
  return s->index.count(std::string((const char *)k, klen)) ? 1 : 0;
}

int64_t hs_store_len(void *sp) {
  return (int64_t)static_cast<Store *>(sp)->index.size();
}

void hs_store_close(void *sp) {
  auto *s = static_cast<Store *>(sp);
  if (s->log) fclose(s->log);
  delete s;
}

void hs_free(void *p) { free(p); }

}  // extern "C"
