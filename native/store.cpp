// Native log-structured KV engine for the Store actor.
//
// The reference's store crate wraps rocksdb behind a single-writer actor
// (store/src/lib.rs:15-92). Here the data plane — hash index, append-only
// length-prefixed log, crash-safe replay that ignores a torn tail — is
// C++; the Python actor (hotstuff_tpu/store/store.py) keeps the channel
// protocol and notify_read obligations and calls in via ctypes.
//
// Log record: <u32 klen><u32 vlen><key><value>, little-endian.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Store {
  std::unordered_map<std::string, std::string> index;
  FILE *log = nullptr;
  bool fsync_writes = false;
};

void replay(Store *s, const char *path) {
  FILE *f = fopen(path, "rb");
  if (!f) return;
  std::vector<uint8_t> hdr(8);
  std::string key, val;
  for (;;) {
    if (fread(hdr.data(), 1, 8, f) != 8) break;
    uint32_t klen, vlen;
    memcpy(&klen, hdr.data(), 4);
    memcpy(&vlen, hdr.data() + 4, 4);
    // guard against a corrupt header at the torn tail
    if (klen > (1u << 20) || vlen > (1u << 28)) break;
    key.resize(klen);
    val.resize(vlen);
    if (klen && fread(&key[0], 1, klen, f) != klen) break;
    if (vlen && fread(&val[0], 1, vlen, f) != vlen) break;
    s->index[key] = val;
  }
  fclose(f);
}

}  // namespace

extern "C" {

void *hs_store_open(const char *path, int fsync_writes) {
  auto *s = new Store;
  s->fsync_writes = fsync_writes != 0;
  if (path && path[0]) {
    replay(s, path);
    s->log = fopen(path, "ab");
    if (!s->log) {
      delete s;
      return nullptr;
    }
  }
  return s;
}

int hs_store_write(void *sp, const uint8_t *k, int64_t klen, const uint8_t *v,
                   int64_t vlen) {
  auto *s = static_cast<Store *>(sp);
  s->index[std::string((const char *)k, klen)] =
      std::string((const char *)v, vlen);
  if (s->log) {
    uint32_t kl = (uint32_t)klen, vl = (uint32_t)vlen;
    if (fwrite(&kl, 4, 1, s->log) != 1) return -1;
    if (fwrite(&vl, 4, 1, s->log) != 1) return -1;
    if (klen && fwrite(k, 1, klen, s->log) != (size_t)klen) return -1;
    if (vlen && fwrite(v, 1, vlen, s->log) != (size_t)vlen) return -1;
    if (fflush(s->log) != 0) return -1;
  }
  return 0;
}

// Returns value length and malloc'd buffer in *out (caller frees via
// hs_free), or -1 if absent.
int64_t hs_store_read(void *sp, const uint8_t *k, int64_t klen,
                      uint8_t **out) {
  auto *s = static_cast<Store *>(sp);
  auto it = s->index.find(std::string((const char *)k, klen));
  if (it == s->index.end()) return -1;
  *out = (uint8_t *)malloc(it->second.size());
  memcpy(*out, it->second.data(), it->second.size());
  return (int64_t)it->second.size();
}

int hs_store_contains(void *sp, const uint8_t *k, int64_t klen) {
  auto *s = static_cast<Store *>(sp);
  return s->index.count(std::string((const char *)k, klen)) ? 1 : 0;
}

int64_t hs_store_len(void *sp) {
  return (int64_t)static_cast<Store *>(sp)->index.size();
}

void hs_store_close(void *sp) {
  auto *s = static_cast<Store *>(sp);
  if (s->log) fclose(s->log);
  delete s;
}

void hs_free(void *p) { free(p); }

}  // extern "C"
