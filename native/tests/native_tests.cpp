// Sanitizer-targeted unit vectors for the native plane (run under
// ASan/UBSan in CI). Correctness against the Python reference staging is
// covered by tests/test_native_staging.py; this binary exercises the C ABI
// surface: store replay / torn-tail truncate / compaction, and staging
// output invariants (digit ranges, canonicality flag).

#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>

extern "C" {
void *hs_store_open(const char *path, int fsync_writes);
int hs_store_write(void *s, const uint8_t *k, int64_t klen, const uint8_t *v,
                   int64_t vlen);
int64_t hs_store_read(void *s, const uint8_t *k, int64_t klen, uint8_t **out);
int hs_store_contains(void *s, const uint8_t *k, int64_t klen);
int64_t hs_store_len(void *s);
int64_t hs_store_compact(void *s);
void hs_store_close(void *s);
void hs_free(void *p);
int hs_stage_batch(const uint8_t *msgs, const int64_t *offsets,
                   const uint8_t *keys, const uint8_t *sigs, int64_t n,
                   float *a_y, float *a_sign, float *r_enc, float *s_digits,
                   float *h_digits, uint8_t *s_ok);
int hs_stage_batch_packed(const uint8_t *msgs, const int64_t *offsets,
                          const uint8_t *keys, const uint8_t *sigs, int64_t n,
                          uint8_t *packed, uint8_t *s_ok);
}

static long file_size(const char *path) {
  struct stat st;
  return stat(path, &st) == 0 ? (long)st.st_size : -1;
}

static void test_store_roundtrip(const char *path) {
  remove(path);
  void *s = hs_store_open(path, 0);
  assert(s);
  assert(hs_store_write(s, (const uint8_t *)"key1", 4, (const uint8_t *)"val1",
                        4) == 0);
  assert(hs_store_write(s, (const uint8_t *)"key2", 4, (const uint8_t *)"",
                        0) == 0);
  uint8_t *out = nullptr;
  assert(hs_store_read(s, (const uint8_t *)"key1", 4, &out) == 4);
  assert(memcmp(out, "val1", 4) == 0);
  hs_free(out);
  assert(hs_store_read(s, (const uint8_t *)"nope", 4, &out) == -1);
  assert(hs_store_contains(s, (const uint8_t *)"key2", 4) == 1);
  assert(hs_store_len(s) == 2);
  hs_store_close(s);

  // replay
  s = hs_store_open(path, 0);
  assert(hs_store_len(s) == 2);
  assert(hs_store_read(s, (const uint8_t *)"key2", 4, &out) == 0);
  hs_free(out);
  hs_store_close(s);
  printf("store roundtrip: ok\n");
}

static void test_store_torn_tail(const char *path) {
  remove(path);
  void *s = hs_store_open(path, 0);
  hs_store_write(s, (const uint8_t *)"a", 1, (const uint8_t *)"1", 1);
  hs_store_write(s, (const uint8_t *)"b", 1, (const uint8_t *)"2", 1);
  hs_store_close(s);
  // tear one byte off the final record
  long sz = file_size(path);
  assert(sz > 0);
  (void)truncate(path, sz - 1);

  s = hs_store_open(path, 0);
  assert(hs_store_contains(s, (const uint8_t *)"a", 1) == 1);
  assert(hs_store_contains(s, (const uint8_t *)"b", 1) == 0);
  // appended records after the truncated tail MUST survive the next replay
  hs_store_write(s, (const uint8_t *)"c", 1, (const uint8_t *)"3", 1);
  hs_store_close(s);
  s = hs_store_open(path, 0);
  assert(hs_store_contains(s, (const uint8_t *)"a", 1) == 1);
  assert(hs_store_contains(s, (const uint8_t *)"c", 1) == 1);
  hs_store_close(s);
  printf("store torn tail: ok\n");
}

static void test_store_compact(const char *path) {
  remove(path);
  void *s = hs_store_open(path, 0);
  std::vector<uint8_t> val(100, 0xAB);
  for (int i = 0; i < 1000; i++) {
    val[0] = (uint8_t)i;
    hs_store_write(s, (const uint8_t *)"hot", 3, val.data(), val.size());
  }
  long before = file_size(path);
  int64_t after = hs_store_compact(s);
  assert(after > 0 && after < before / 10);
  uint8_t *out = nullptr;
  assert(hs_store_read(s, (const uint8_t *)"hot", 3, &out) == 100);
  assert(out[0] == (uint8_t)231);  // 999 & 0xFF
  hs_free(out);
  // writes still work after compaction and survive replay
  hs_store_write(s, (const uint8_t *)"post", 4, val.data(), 4);
  hs_store_close(s);
  s = hs_store_open(path, 0);
  assert(hs_store_contains(s, (const uint8_t *)"post", 4) == 1);
  assert(hs_store_len(s) == 2);
  hs_store_close(s);
  printf("store compact: ok (%ld -> %lld bytes)\n", before, (long long)after);
}

static void test_staging_invariants() {
  const int64_t n = 2;
  uint8_t msgs[64];
  for (int i = 0; i < 64; i++) msgs[i] = (uint8_t)i;
  int64_t offsets[3] = {0, 32, 64};
  uint8_t keys[64], sigs[128];
  for (int i = 0; i < 64; i++) keys[i] = (uint8_t)(i * 3 + 1);
  for (int i = 0; i < 128; i++) sigs[i] = (uint8_t)(i * 5 + 7);
  // item 1: s = 0xFF... (>= L): must be flagged non-canonical
  memset(sigs + 96, 0xFF, 32);

  std::vector<float> a_y(32 * n), a_sign(n), r_enc(32 * n), s_digits(64 * n),
      h_digits(64 * n);
  std::vector<uint8_t> s_ok(n);
  int rc = hs_stage_batch(msgs, offsets, keys, sigs, n, a_y.data(),
                          a_sign.data(), r_enc.data(), s_digits.data(),
                          h_digits.data(), s_ok.data());
  assert(rc == 0);
  for (float d : s_digits) assert(d >= 0.0f && d < 16.0f);
  for (float d : h_digits) assert(d >= 0.0f && d < 16.0f);
  for (float v : a_y) assert(v >= 0.0f && v < 256.0f);
  for (int64_t i = 0; i < n; i++) assert(a_sign[i] == 0.0f || a_sign[i] == 1.0f);
  assert(s_ok[1] == 0);  // s >= L rejected
  printf("staging invariants: ok\n");
}

static void test_packed_staging_matches_f32() {
  // The packed (128, n) u8 wire rows must agree with the f32 staging of the
  // same inputs: rows 0-31 = raw A, 32-63 = raw R, 64-95 = raw S, and the
  // h rows' nibbles must equal h_digits.
  const int64_t n = 3;
  uint8_t msgs[96];
  for (int i = 0; i < 96; i++) msgs[i] = (uint8_t)(i ^ 0x5A);
  int64_t offsets[4] = {0, 32, 64, 96};
  uint8_t keys[96], sigs[192];
  for (int i = 0; i < 96; i++) keys[i] = (uint8_t)(i * 7 + 3);
  for (int i = 0; i < 192; i++) sigs[i] = (uint8_t)(i * 11 + 5);
  memset(sigs + 32 + 16, 0x00, 16);  // keep item 0's s < L

  std::vector<float> a_y(32 * n), a_sign(n), r_enc(32 * n), s_digits(64 * n),
      h_digits(64 * n);
  std::vector<uint8_t> s_ok_f(n), s_ok_p(n), packed(128 * n);
  assert(hs_stage_batch(msgs, offsets, keys, sigs, n, a_y.data(),
                        a_sign.data(), r_enc.data(), s_digits.data(),
                        h_digits.data(), s_ok_f.data()) == 0);
  assert(hs_stage_batch_packed(msgs, offsets, keys, sigs, n, packed.data(),
                               s_ok_p.data()) == 0);
  for (int64_t b = 0; b < n; b++) {
    assert(s_ok_f[b] == s_ok_p[b]);
    for (int i = 0; i < 32; i++) {
      assert(packed[(int64_t)i * n + b] == keys[32 * b + i]);           // A
      assert(packed[(32 + (int64_t)i) * n + b] == sigs[64 * b + i]);    // R
      assert(packed[(64 + (int64_t)i) * n + b] == sigs[64 * b + 32 + i]);  // S
      uint8_t h = packed[(96 + (int64_t)i) * n + b];
      assert((float)(h & 0x0F) == h_digits[(int64_t)(2 * i) * n + b]);
      assert((float)(h >> 4) == h_digits[(int64_t)(2 * i + 1) * n + b]);
    }
  }
  printf("packed staging matches f32: ok\n");
}

int main() {
  test_store_roundtrip("/tmp/hs_native_test_store.log");
  test_store_torn_tail("/tmp/hs_native_test_torn.log");
  test_store_compact("/tmp/hs_native_test_compact.log");
  test_staging_invariants();
  test_packed_staging_matches_f32();
  printf("ALL NATIVE TESTS PASSED\n");
  return 0;
}
