// Native batch-staging plane for the TPU ed25519 kernel.
//
// The Python host staging (ops/ed25519.prepare_batch: per-item SHA-512 of
// R||A||M, mod-L reduction, limb/digit extraction) caps end-to-end
// throughput at ~13k sigs/s while the TPU kernel does 72k+. This C++ path
// does the whole batch in one call over raw buffers (ctypes, no CPython
// API), the equivalent of the data-plane work the reference gets from
// native Rust (crypto/src/lib.rs; SURVEY.md §2 "native component" rule).
//
// Self-contained SHA-512 (FIPS 180-4; constants generated exactly by
// gen_constants.py) and a fold-based scalar reduction mod the ed25519
// group order L. Cross-checked against hashlib/Python ints in
// tests/test_native_staging.py.

#include <cstdint>
#include <cstring>

#include "constants.h"

typedef unsigned __int128 u128;

// ---------------------------------------------------------------------------
// SHA-512
// ---------------------------------------------------------------------------

static inline uint64_t rotr(uint64_t x, int n) {
  return (x >> n) | (x << (64 - n));
}

static void sha512_compress(uint64_t st[8], const uint8_t *block) {
  uint64_t w[80];
  for (int i = 0; i < 16; i++) {
    w[i] = 0;
    for (int j = 0; j < 8; j++) w[i] = (w[i] << 8) | block[8 * i + j];
  }
  for (int i = 16; i < 80; i++) {
    uint64_t s0 = rotr(w[i - 15], 1) ^ rotr(w[i - 15], 8) ^ (w[i - 15] >> 7);
    uint64_t s1 = rotr(w[i - 2], 19) ^ rotr(w[i - 2], 61) ^ (w[i - 2] >> 6);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint64_t a = st[0], b = st[1], c = st[2], d = st[3];
  uint64_t e = st[4], f = st[5], g = st[6], h = st[7];
  for (int i = 0; i < 80; i++) {
    uint64_t S1 = rotr(e, 14) ^ rotr(e, 18) ^ rotr(e, 41);
    uint64_t ch = (e & f) ^ (~e & g);
    uint64_t t1 = h + S1 + ch + SHA512_K[i] + w[i];
    uint64_t S0 = rotr(a, 28) ^ rotr(a, 34) ^ rotr(a, 39);
    uint64_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint64_t t2 = S0 + maj;
    h = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  st[0] += a; st[1] += b; st[2] += c; st[3] += d;
  st[4] += e; st[5] += f; st[6] += g; st[7] += h;
}

static void sha512(const uint8_t *parts[], const size_t lens[], int nparts,
                   uint8_t out[64]) {
  uint64_t st[8];
  memcpy(st, SHA512_H0, sizeof(st));
  uint8_t buf[128];
  size_t fill = 0;
  uint64_t total = 0;
  for (int p = 0; p < nparts; p++) {
    const uint8_t *data = parts[p];
    size_t len = lens[p];
    total += len;
    while (len > 0) {
      size_t take = 128 - fill;
      if (take > len) take = len;
      memcpy(buf + fill, data, take);
      fill += take; data += take; len -= take;
      if (fill == 128) { sha512_compress(st, buf); fill = 0; }
    }
  }
  // padding: 0x80, zeros, 128-bit big-endian bit length
  buf[fill++] = 0x80;
  if (fill > 112) {
    memset(buf + fill, 0, 128 - fill);
    sha512_compress(st, buf);
    fill = 0;
  }
  memset(buf + fill, 0, 112 - fill);
  uint64_t bits = total * 8;
  memset(buf + 112, 0, 8);  // we never hash > 2^64 bits
  for (int i = 0; i < 8; i++) buf[127 - i] = (uint8_t)(bits >> (8 * i));
  sha512_compress(st, buf);
  for (int i = 0; i < 8; i++)
    for (int j = 0; j < 8; j++) out[8 * i + j] = (uint8_t)(st[i] >> (56 - 8 * j));
}

// ---------------------------------------------------------------------------
// Scalar arithmetic mod L (little-endian 64-bit limbs)
// ---------------------------------------------------------------------------

static const int NL = 8;  // working width, 512 bits

static int ge_l(const uint64_t x[NL]) {
  for (int i = NL - 1; i >= 4; i--)
    if (x[i]) return 1;
  for (int i = 3; i >= 0; i--) {
    if (x[i] > L_LIMBS[i]) return 1;
    if (x[i] < L_LIMBS[i]) return 0;
  }
  return 1;  // equal
}

static void sub_l(uint64_t x[NL]) {
  uint64_t borrow = 0;
  for (int i = 0; i < NL; i++) {
    uint64_t li = (i < 4) ? L_LIMBS[i] : 0;
    u128 t = (u128)x[i] - li - borrow;
    x[i] = (uint64_t)t;
    borrow = (t >> 64) ? 1 : 0;
  }
}

// 64-byte little-endian value -> value mod L, little-endian 32 bytes.
//
// Three rounds of the split-at-252 fold: x = hi*2^252 + lo with
// 2^252 = -c (mod L), so x = lo + MBIAS[r] - hi*c where MBIAS[r] is a
// precomputed multiple of L exceeding the round's max hi*c (keeps all
// arithmetic nonnegative). Sizes: 2^512 -> <2^387 -> <2^261 -> <2^254,
// then at most three final subtractions of L.
static void reduce_mod_l(const uint8_t in[64], uint8_t out[32]) {
  uint64_t x[NL];
  for (int i = 0; i < NL; i++) {
    uint64_t v = 0;
    for (int j = 7; j >= 0; j--) v = (v << 8) | in[8 * i + j];
    x[i] = v;
  }
  for (int round = 0; round < 3; round++) {
    // hi = x >> 252 (up to 5 limbs), lo = x & (2^252 - 1)
    uint64_t hi[5];
    for (int i = 0; i < 5; i++) {
      uint64_t lo64 = (i + 3 < NL) ? x[i + 3] : 0;
      uint64_t hi64 = (i + 4 < NL) ? x[i + 4] : 0;
      hi[i] = (lo64 >> 60) | (hi64 << 4);
    }
    uint64_t acc[NL] = {x[0], x[1], x[2], x[3] & 0x0FFFFFFFFFFFFFFFULL,
                        0, 0, 0, 0};
    // acc += MBIAS[round]
    u128 carry = 0;
    for (int i = 0; i < NL; i++) {
      u128 t = (u128)acc[i] + (i < 7 ? MBIAS[round][i] : 0) + carry;
      acc[i] = (uint64_t)t;
      carry = t >> 64;
    }
    // acc -= hi * c   (c = 2 limbs; product <= 7 limbs)
    uint64_t prod[NL] = {0};
    for (int i = 0; i < 5; i++) {
      u128 c2 = 0;
      for (int j = 0; j < 2; j++) {
        u128 t = (u128)hi[i] * C_LIMBS[j] + prod[i + j] + c2;
        prod[i + j] = (uint64_t)t;
        c2 = t >> 64;
      }
      for (int k = i + 2; k < NL && c2; k++) {
        u128 t = (u128)prod[k] + c2;
        prod[k] = (uint64_t)t;
        c2 = t >> 64;
      }
    }
    uint64_t borrow = 0;
    for (int i = 0; i < NL; i++) {
      u128 t = (u128)acc[i] - prod[i] - borrow;
      x[i] = (uint64_t)t;
      borrow = (t >> 64) ? 1 : 0;
    }
  }
  while (ge_l(x)) sub_l(x);
  for (int i = 0; i < 4; i++)
    for (int j = 0; j < 8; j++) out[8 * i + j] = (uint8_t)(x[i] >> (8 * j));
}

static int lt_l_bytes(const uint8_t s[32]) {
  uint64_t x[NL] = {0};
  for (int i = 0; i < 4; i++) {
    uint64_t v = 0;
    for (int j = 7; j >= 0; j--) v = (v << 8) | s[8 * i + j];
    x[i] = v;
  }
  return !ge_l(x);
}

// ---------------------------------------------------------------------------
// Batch staging entry point
// ---------------------------------------------------------------------------

extern "C" int hs_stage_batch(
    const uint8_t *msgs,        // concatenated message bytes
    const int64_t *msg_offsets, // n+1 offsets into msgs
    const uint8_t *keys,        // n * 32
    const uint8_t *sigs,        // n * 64
    int64_t n,
    float *a_y,      // (32, n) row-major
    float *a_sign,   // (n,)
    float *r_enc,    // (32, n)
    float *s_digits, // (64, n)
    float *h_digits, // (64, n)
    uint8_t *s_ok    // (n,)
) {
  for (int64_t b = 0; b < n; b++) {
    const uint8_t *A = keys + 32 * b;
    const uint8_t *R = sigs + 64 * b;
    const uint8_t *S = sigs + 64 * b + 32;

    for (int i = 0; i < 32; i++) {
      uint8_t ai = (i == 31) ? (uint8_t)(A[i] & 0x7f) : A[i];
      a_y[(int64_t)i * n + b] = (float)ai;
      r_enc[(int64_t)i * n + b] = (float)R[i];
    }
    a_sign[b] = (float)(A[31] >> 7);
    s_ok[b] = (uint8_t)lt_l_bytes(S);

    const uint8_t *parts[3] = {R, A, msgs + msg_offsets[b]};
    const size_t lens[3] = {32, 32,
                            (size_t)(msg_offsets[b + 1] - msg_offsets[b])};
    uint8_t hd[64], hred[32];
    sha512(parts, lens, 3, hd);
    reduce_mod_l(hd, hred);

    for (int i = 0; i < 32; i++) {
      s_digits[(int64_t)(2 * i) * n + b] = (float)(S[i] & 0x0f);
      s_digits[(int64_t)(2 * i + 1) * n + b] = (float)(S[i] >> 4);
      h_digits[(int64_t)(2 * i) * n + b] = (float)(hred[i] & 0x0f);
      h_digits[(int64_t)(2 * i + 1) * n + b] = (float)(hred[i] >> 4);
    }
  }
  return 0;
}

// Packed wire-format staging: one (128, n) u8 row-major array
// (rows 0-31 = A, 32-63 = R, 64-95 = S, 96-127 = h = SHA-512(R||A||M) mod L)
// shipped to the device as-is and unpacked there (ops/ed25519
// unpack_packed_inputs). 128 B/signature vs 772 B for the f32 arguments —
// the transfer reduction that makes the pipelined end-to-end path
// device-bound instead of transfer-bound on low-bandwidth host<->TPU links.
extern "C" int hs_stage_batch_packed(
    const uint8_t *msgs,        // concatenated message bytes
    const int64_t *msg_offsets, // n+1 offsets into msgs
    const uint8_t *keys,        // n * 32
    const uint8_t *sigs,        // n * 64
    int64_t n,
    uint8_t *packed, // (128, n) row-major
    uint8_t *s_ok    // (n,)
) {
  uint8_t *rows_a = packed;
  uint8_t *rows_r = packed + 32 * n;
  uint8_t *rows_s = packed + 64 * n;
  uint8_t *rows_h = packed + 96 * n;
  for (int64_t b = 0; b < n; b++) {
    const uint8_t *A = keys + 32 * b;
    const uint8_t *R = sigs + 64 * b;
    const uint8_t *S = sigs + 64 * b + 32;
    for (int i = 0; i < 32; i++) {
      rows_a[(int64_t)i * n + b] = A[i];
      rows_r[(int64_t)i * n + b] = R[i];
      rows_s[(int64_t)i * n + b] = S[i];
    }
    s_ok[b] = (uint8_t)lt_l_bytes(S);

    const uint8_t *parts[3] = {R, A, msgs + msg_offsets[b]};
    const size_t lens[3] = {32, 32,
                            (size_t)(msg_offsets[b + 1] - msg_offsets[b])};
    uint8_t hd[64], hred[32];
    sha512(parts, lens, 3, hd);
    reduce_mod_l(hd, hred);
    for (int i = 0; i < 32; i++) rows_h[(int64_t)i * n + b] = hred[i];
  }
  return 0;
}

// Standalone helpers (exported for tests)
extern "C" void hs_sha512(const uint8_t *data, int64_t len, uint8_t out[64]) {
  const uint8_t *parts[1] = {data};
  const size_t lens[1] = {(size_t)len};
  sha512(parts, lens, 1, out);
}

extern "C" void hs_reduce_mod_l(const uint8_t in[64], uint8_t out[32]) {
  reduce_mod_l(in, out);
}
