"""Benchmark task entry points (reference benchmark/fabfile.py:11-157).

With Fabric installed these are `fab local`, `fab plot`, etc.; without it,
`python -m benchmark.run_local` drives the same code (this environment has no
Fabric). Remote/AWS tasks require boto3+fabric and raise a clear error when
missing.
"""

from __future__ import annotations

try:  # Fabric is optional (absent in this environment).
    from fabric import task
except ImportError:  # pragma: no cover

    def task(fn):
        return fn


from .local import LocalBench
from .logs import LogParser

# Reference-default local parameters (fabfile.py:14-34).
LOCAL_BENCH_PARAMS = {
    "nodes": 4,
    "rate": 1_000,
    "tx_size": 512,
    "faults": 0,
    "duration": 20,
}
LOCAL_NODE_PARAMS = {
    "consensus": {
        "timeout_delay": 1_000,
        "sync_retry_delay": 10_000,
        "max_payload_size": 1_000,
        "min_block_delay": 0,
    },
    "mempool": {
        "queue_capacity": 10_000,
        "sync_retry_delay": 10_000,
        "max_payload_size": 15_000,
        "min_block_delay": 0,
    },
}

# Reference-default remote sweep (fabfile.py:99-120).
REMOTE_BENCH_PARAMS = {
    "nodes": [10, 20],
    "rate": [25_000, 50_000],
    "tx_size": 512,
    "faults": 0,
    "duration": 300,
    "runs": 2,
}


@task
def local(ctx=None, debug=False, crypto="cpu"):
    """Run a benchmark on localhost (fabfile.py:11-34)."""
    params = dict(LOCAL_BENCH_PARAMS, crypto=crypto)
    parser = LocalBench(params, LOCAL_NODE_PARAMS).run(debug=bool(debug))
    print(parser.result())
    return parser


@task
def logs(ctx=None, directory="logs", faults=0):
    """Parse an existing logs directory (fabfile.py:150-157)."""
    parser = LogParser.process(directory, int(faults))
    print(parser.result())
    return parser


@task
def aggregate(ctx=None, directory="results"):
    """Aggregate result files (reference aggregate.py)."""
    from .aggregate import aggregate_results

    aggregate_results(directory)


@task
def plot(ctx=None, directory="results"):
    """Plot aggregated results (reference plot.py)."""
    from .plot import plot_results

    plot_results(directory)


def _require_aws():
    raise RuntimeError(
        "remote/AWS tasks need boto3 + fabric, which are not installed in "
        "this environment; see benchmark/aws/ for the implementation"
    )


@task
def create(ctx=None, nodes=2):
    """Create AWS testbed (fabfile.py:36-47)."""
    from .aws.instance import InstanceManager

    InstanceManager.make().create_instances(int(nodes))


@task
def destroy(ctx=None):
    from .aws.instance import InstanceManager

    InstanceManager.make().terminate_instances()


@task
def install(ctx=None):
    from .aws.remote import Bench

    Bench().install()


@task
def remote(ctx=None, debug=False, crypto="cpu"):
    from .aws.remote import Bench

    Bench().run(
        REMOTE_BENCH_PARAMS, LOCAL_NODE_PARAMS, debug=bool(debug), crypto=crypto
    )


@task
def kill(ctx=None):
    import subprocess

    from .commands import CommandMaker

    subprocess.run(CommandMaker.kill(), shell=True)
