"""Canonical shell command strings (reference benchmark/benchmark/commands.py:6-56).

The reference aliases compiled Rust binaries; here the "binaries" are the
package entry points run with the current interpreter.
"""

from __future__ import annotations

import sys
from os.path import join


class CommandMaker:
    @staticmethod
    def cleanup() -> str:
        return "rm -rf .db-* ; rm -f .*.json ; mkdir -p logs"

    @staticmethod
    def clean_logs() -> str:
        return "rm -rf logs ; mkdir -p logs"

    @staticmethod
    def compile() -> str:
        # No compilation for the Python path; the native plane builds via make.
        return f"{sys.executable} -c 'import hotstuff_tpu'"

    @staticmethod
    def generate_key(filename: str) -> str:
        return f"{sys.executable} -m hotstuff_tpu.node.main keys --filename {filename}"

    @staticmethod
    def run_node(keys: str, committee: str, store: str, parameters: str, crypto: str = "cpu", crypto_addr: str | None = None, debug: bool = False) -> str:
        v = "-vvv" if debug else "-vv"
        addr = f" --crypto-addr {crypto_addr}" if crypto_addr else ""
        return (
            f"{sys.executable} -m hotstuff_tpu.node.main {v} run "
            f"--keys {keys} --committee {committee} --store {store} "
            f"--parameters {parameters} --crypto {crypto}{addr}"
        )

    @staticmethod
    def run_sidecar(
        port: int,
        backend: str = "tpu",
        debug: bool = False,
        chunk: int | None = None,
        committee: str | None = None,
    ) -> str:
        """The shared crypto sidecar: one process owns the TPU; all local
        nodes ship their large verification batches to it. `committee`
        points at the node committee file so the sidecar registers the
        validator keys as device-resident precompute at boot (the
        committee-tagged batches it serves then ride the
        zero-decompression kernel)."""
        v = "-vvv" if debug else "-vv"
        chunk_arg = f" --chunk {chunk}" if chunk is not None else ""
        committee_arg = f" --committee {committee}" if committee else ""
        return (
            f"{sys.executable} -m hotstuff_tpu.crypto.remote {v} "
            f"--port {port} --backend {backend}{chunk_arg}{committee_arg}"
        )

    @staticmethod
    def run_client(address: str, size: int, rate: int, nodes: list[str], duration: float | None = None) -> str:
        nodes_arg = f" --nodes {' '.join(nodes)}" if nodes else ""
        dur = f" --duration {duration}" if duration is not None else ""
        return (
            f"{sys.executable} -m hotstuff_tpu.node.client -vv {address} "
            f"--size {size} --rate {rate}{nodes_arg}{dur}"
        )

    @staticmethod
    def kill() -> str:
        # covers node, client, AND the crypto sidecar (hotstuff_tpu.crypto.remote)
        return "pkill -f 'hotstuff_tpu.node' ; pkill -f 'hotstuff_tpu.crypto.remote' || true"

    @staticmethod
    def logs_path(directory: str, kind: str, i: int) -> str:
        return join(directory, f"{kind}-{i}.log")
