"""Log parsing: the metrics pipeline (reference benchmark/benchmark/logs.py).

Regex-scrapes node and client logs to compute:
  * consensus TPS/BPS and latency (block Created -> Committed)
  * end-to-end TPS/BPS and latency (client sample send -> commit), via the
    sample-tx -> payload-digest -> block-commit join (logs.py:102-104,173-182)
  * benchmark-workload verification throughput (the fork's
    "Verifying OWN/OTHER transaction batch. Size: N" lines -- the
    votes-verified/sec north-star metric)

Raises ParseError if any log contains a traceback, actor crash, or an
ERROR-severity line, like the reference raising on `Error`/`panic` matches
(logs.py:71-72,88-89). Per-log scraping runs in a multiprocessing Pool when
the host has cores to spare (reference logs.py:27-39) — at 20+ node log
volumes the regex pass is minutes of single-core work.
"""

from __future__ import annotations

import json
import os
import re
from datetime import datetime, timezone
from glob import glob
from multiprocessing import Pool
from os.path import join
from statistics import mean


class ParseError(Exception):
    pass


def _check_crash(text: str) -> None:
    if (
        "Traceback" in text
        or " ERROR " in text
        or "panic" in text
        or ("actor" in text and "crashed" in text)
    ):
        raise ParseError("node or client log contains a crash or error")


_TS = r"\[(\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}\.\d{3})Z"


def _to_posix(ts: str) -> float:
    return (
        datetime.strptime(ts, "%Y-%m-%dT%H:%M:%S.%f")
        .replace(tzinfo=timezone.utc)
        .timestamp()
    )


def _search_all(pattern: str, text: str) -> list[tuple]:
    return re.findall(pattern, text, re.MULTILINE)


def _parse_client(text: str) -> dict:
    """Scrape one client log (runs in a Pool worker)."""
    _check_crash(text)
    out: dict = {"size": 0, "rate": 0, "start": None, "samples": {}, "misses": 0}
    m = re.search(rf"{_TS}.*Transactions size: (\d+) B", text)
    if m:
        out["size"] = int(m.group(2))
    m = re.search(rf"{_TS}.*Transactions rate: (\d+) tx/s", text)
    if m:
        out["rate"] = int(m.group(2))
    m = re.search(rf"{_TS}.*Start sending transactions", text)
    if m:
        out["start"] = _to_posix(m.group(1))
    out["samples"] = {
        int(sid): _to_posix(ts)
        for ts, sid in _search_all(
            rf"{_TS}.*Sending sample transaction (\d+)", text
        )
    }
    out["misses"] = len(_search_all(r"rate too high", text))
    # Ingress load-generator result lines (hotstuff_tpu/ingress/loadgen.py
    # log_summary): open-loop offered/accepted/shed counts and
    # client-observed latency percentiles. Absent on Front-only runs.
    for pat, key, cast in [
        (r"Ingress offered: (\d+) transactions", "ingress_offered", int),
        (r"Ingress accepted: (\d+) transactions", "ingress_accepted", int),
        (r"Ingress shed: (\d+) transactions", "ingress_shed", int),
        (r"Ingress client latency p50: ([\d.]+) ms", "ingress_p50", float),
        (r"Ingress client latency p99: ([\d.]+) ms", "ingress_p99", float),
    ]:
        m = re.search(pat, text)
        out[key] = cast(m.group(1)) if m else None
    return out


def _parse_node(text: str) -> dict:
    """Scrape one node log (runs in a Pool worker)."""
    _check_crash(text)
    out: dict = {
        "proposals": {},
        "commits": {},
        "committed_payloads": {},
        "payload_sizes": {},
        "sample_to_payload": {},
        "verif_batches": [],
        "timeouts": 0,
    }
    for ts, rnd, digest in _search_all(rf"{_TS}.*Created B(\d+)\((\S+?)\)$", text):
        t = _to_posix(ts)
        out["proposals"][digest] = min(out["proposals"].get(digest, t), t)
    for ts, rnd, digest in _search_all(rf"{_TS}.*Committed B(\d+)\((\S+?)\)$", text):
        t = _to_posix(ts)
        out["commits"][digest] = min(out["commits"].get(digest, t), t)
    for ts, rnd, digest, payload in _search_all(
        rf"{_TS}.*Committed B(\d+)\((\S+?)\) -> (\S+)$", text
    ):
        t = _to_posix(ts)
        prev = out["committed_payloads"].get(payload)
        if prev is None or t < prev[1]:
            out["committed_payloads"][payload] = (digest, t)
    for ts, payload, size in _search_all(
        rf"{_TS}.*Payload (\S+) contains (\d+) B", text
    ):
        out["payload_sizes"][payload] = int(size)
    for ts, payload, sid in _search_all(
        rf"{_TS}.*Payload (\S+) contains sample tx (\d+)", text
    ):
        out["sample_to_payload"][int(sid)] = payload
    for ts, kind, n in _search_all(
        rf"{_TS}.*Verifying (OWN|OTHER) transaction batch\. Size: (\d+)", text
    ):
        out["verif_batches"].append((_to_posix(ts), int(n)))
    out["timeouts"] = len(_search_all(r"Timeout reached", text))
    # Cumulative count from the periodic saturation warning. The LAST
    # logged milestone is a LOWER BOUND on the node's total shed (the node
    # is killed by SIGTERM, so up to one 25k-milestone of tail sheds goes
    # unlogged); 0 when never saturated.
    shed = _search_all(r"(\d+) synthetic workload signatures skipped", text)
    # single-group findall yields plain strings
    out["workload_shed"] = int(shed[-1]) if shed else 0
    # Anomaly-watchdog firings (utils/tracing.py): reasons + dump paths.
    # A fired watchdog is the signal a run's numbers need the recorder
    # dump read before being believed.
    out["watchdog_fired"] = _search_all(
        r"anomaly watchdog fired: (\w+)", text
    )
    out["watchdog_dumps"] = _search_all(
        r"flight recorder dumped to (\S+)", text
    )
    # Live-telemetry lines (utils/telemetry.py): SLO burn alert
    # transitions and the periodic device-occupancy line. Occupancy is
    # cumulative over the timeline ring, so only the LAST line per node
    # matters.
    out["slo_fired"] = _search_all(r"SLO burn fired: (\S+)", text)
    out["slo_cleared"] = _search_all(r"SLO burn cleared: (\S+)", text)
    # Incident-ledger lines (utils/incidents.py §5.5r): the run-level
    # fault→alert→recovery summary and the burn-budget verdict. One
    # summary per ledger build; the LAST line wins (a rerun supersedes).
    inc = _search_all(
        r"Incident ledger: (\d+) incident\(s\), (\d+) alert\(s\) "
        r"attributed, (\d+) unattributed, (\d+) residual, "
        r"worst MTTR ([\d.]+) ms",
        text,
    )
    out["incident_ledger"] = (
        (
            int(inc[-1][0]),
            int(inc[-1][1]),
            int(inc[-1][2]),
            int(inc[-1][3]),
            float(inc[-1][4]),
        )
        if inc
        else None
    )
    burn = _search_all(
        r"Burn budget verdict: (ok|violated) "
        r"\((\d+) SLO row\(s\) over budget\)",
        text,
    )
    out["burn_verdict"] = (burn[-1][0], int(burn[-1][1])) if burn else None
    # Reconfiguration / catch-up lines (consensus/reconfig.py +
    # synchronizer.py + core.py): epoch switches with their activation
    # rounds, and range-sync start lag / fetched-block progress.
    out["epoch_switches"] = [
        (int(e), int(r))
        for e, r in _search_all(
            r"Epoch switch to (\d+) at activation round (\d+)", text
        )
    ]
    # Epoch-final handoff lines (consensus/reconfig.py §5.5j): one per
    # committed rotation with the commit-to-boundary slack, plus the
    # hard-invariant violation marker (which must normally never appear).
    out["handoffs"] = [
        (int(e), int(t_), int(b), int(s))
        for e, t_, b, s in _search_all(
            r"Epoch handoff to (\d+) committed at round (\d+) "
            r"\(boundary (\d+), slack (\d+) rounds\)",
            text,
        )
    ]
    out["handoff_violations"] = len(
        _search_all(r"Epoch handoff VIOLATION", text)
    )
    out["range_lags"] = [
        int(lag)
        for lag in _search_all(
            r"Range sync started for \S+: (\d+) rounds behind", text
        )
    ]
    out["range_blocks"] = sum(
        int(n) for n in _search_all(r"Range sync fetched (\d+) blocks", text)
    )
    # Aggregation-overlay lines (consensus/overlay.py + core.py): partial
    # bundles that completed a certificate, and gossip fallbacks fired
    # when a round stayed stalled past the fallback window.
    out["agg_quorums"] = [
        (kind, int(rnd), int(entries))
        for kind, rnd, entries in _search_all(
            r"Agg bundle quorum: (QC|TC) round (\d+) from (\d+) entries", text
        )
    ]
    out["agg_fallbacks"] = [
        (int(rnd), int(entries), int(peers))
        for rnd, entries, peers in _search_all(
            r"Agg fallback round (\d+): (\d+) entries to (\d+) peers", text
        )
    ]
    # Certificate-plane line (consensus/core.py _commit): cumulative
    # aggregate-vs-entry-list cert counts, the worst committed cert's
    # wire bytes, and the deepest aggregation merge tree seen. Cumulative
    # per node, so the LAST line wins.
    certs = _search_all(
        r"Cert plane: (\d+) aggregate / (\d+) entry-list certs committed, "
        r"worst cert (\d+) B, agg depth (\d+)",
        text,
    )
    out["cert_plane"] = (
        tuple(int(x) for x in certs[-1]) if certs else None
    )
    # Proof-plane line (proofs/server.py _serve): cumulative served /
    # subscription / shed counts and the worst served proof's wire bytes.
    # Cumulative per node, so the LAST line wins; absent on runs without
    # the commit-proof serving plane.
    served = _search_all(
        r"Proof served: (\d+) proofs served, (\d+) subscriptions, "
        r"(\d+) shed, worst proof (\d+) B",
        text,
    )
    out["proof_plane"] = (
        tuple(int(x) for x in served[-1]) if served else None
    )
    # Election-plane line (consensus/core.py _note_election_stats): the
    # per-node cumulative propose->certify pivot attribution — rounds
    # scored, co-located pivots, cross-region hops, and the in-run
    # round-robin counterfactual. Cumulative per node, so the LAST line
    # wins; absent (None, never zeros) when the run had no region map.
    elect = _search_all(
        r"Election plane: (\d+) round\(s\) committed, (\d+) co-located "
        r"pivot\(s\), (\d+) cross-region hop\(s\), (\d+) blind",
        text,
    )
    out["election"] = tuple(int(x) for x in elect[-1]) if elect else None
    # Network-observatory lines (consensus/core.py _log_peer_map): the
    # periodic per-vantage RTT map and cumulative probe counters. Both
    # are cumulative/monotone per node, so the LAST line wins — except
    # the worst EWMA, which keeps the max ever logged (a link that
    # degraded mid-run and recovered still counts as the worst seen).
    rtt_maps = _search_all(
        r"Peer RTT map: (\d+) peer\(s\) in (\d+) class\(es\), "
        r"worst EWMA ([\d.]+) ms",
        text,
    )
    out["peer_rtt"] = (
        (
            int(rtt_maps[-1][0]),
            int(rtt_maps[-1][1]),
            max(float(w) for _p, _c, w in rtt_maps),
        )
        if rtt_maps
        else None
    )
    probes = _search_all(r"Probe summary: (\d+) sent, (\d+) answered", text)
    out["probes"] = (int(probes[-1][0]), int(probes[-1][1])) if probes else None
    # Scenario-matrix result lines (tools/chaos_run.py --matrix): per-cell
    # verdicts, green->red regressions against the committed baseline
    # artifact, and the worst per-cell commit-rate delta.
    out["matrix_cells"] = [
        (cell, verdict)
        for cell, verdict in _search_all(
            r"MATRIX cell (\S+) (green|red) ", text
        )
    ]
    out["matrix_regressions"] = _search_all(
        r"MATRIX regression: (\S+) went red", text
    )
    out["matrix_worst"] = [
        (cell, float(pct))
        for cell, pct in _search_all(
            r"MATRIX worst regression: (\S+) commit rate ([+-]?[\d.]+)%", text
        )
    ]
    # Static-analysis summary line (tools/graftlint): deploy/CI recipes
    # run the lint before boot and tee its summary into the log. The
    # LAST line wins (a rerun supersedes); absent on unlinted runs.
    lint = _search_all(r"graftlint: (\d+) findings", text)
    out["graftlint_findings"] = int(lint[-1]) if lint else None
    occ = _search_all(
        r"TELEMETRY device occupancy ([\d.]+)% overlap headroom ([\d.]+)%",
        text,
    )
    out["occupancy"] = (
        (float(occ[-1][0]), float(occ[-1][1])) if occ else None
    )
    # METRICS snapshot lines (utils/metrics.py periodic emitter). Counters
    # are cumulative, so only the LAST well-formed snapshot per node
    # matters; a malformed blob (truncated by SIGTERM mid-line) is skipped,
    # never a ParseError — observability must not fail the run.
    out["metrics"] = None
    for blob in reversed(_search_all(r"METRICS (\{.*\})\s*$", text)):
        try:
            snap = json.loads(blob)
        except json.JSONDecodeError:
            continue
        if isinstance(snap, dict):
            out["metrics"] = snap
            break
    return out


def _map_logs(fn, texts: list[str]) -> list[dict]:
    """Pool-parallel per-log scraping (reference logs.py:27-39); serial when
    the host is single-core or there is nothing to parallelise."""
    if len(texts) > 1 and (os.cpu_count() or 1) > 1:
        with Pool() as p:
            return p.map(fn, texts)
    return [fn(t) for t in texts]


class LogParser:
    def __init__(self, clients: list[str], nodes: list[str], faults: int = 0) -> None:
        self.faults = faults
        self.committee_size = len(nodes) + faults

        # --- client logs ---
        self.size = 0
        self.rate = 0
        self.start = None
        # Steady-state window start: the LAST client's first send. On real
        # distributed hardware clients start within ~a second and this
        # equals `start`; on an oversubscribed single-core host client
        # interpreters can take minutes to boot, and measuring from the
        # FIRST client would fold the partial-load ramp into the TPS
        # denominator (deflating large committees arbitrarily).
        self.steady_start = None
        self.sent_samples: dict[tuple[int, int], float] = {}
        self.misses = 0
        self.ingress_offered = 0
        self.ingress_accepted = 0
        self.ingress_shed = 0
        # Percentiles are not mergeable across clients: keep per-client
        # values and report mean p50 / worst p99.
        self.ingress_p50s: list[float] = []
        self.ingress_p99s: list[float] = []
        for i, c in enumerate(_map_logs(_parse_client, clients)):
            self.size = self.size or c["size"]
            self.rate += c["rate"]
            if c["start"] is not None:
                self.start = (
                    c["start"] if self.start is None else min(self.start, c["start"])
                )
                self.steady_start = (
                    c["start"]
                    if self.steady_start is None
                    else max(self.steady_start, c["start"])
                )
            # Sample ids collide across clients; key by (client, id).
            for sid, t in c["samples"].items():
                self.sent_samples[(i, sid)] = t
            self.misses += c["misses"]
            self.ingress_offered += c.get("ingress_offered") or 0
            self.ingress_accepted += c.get("ingress_accepted") or 0
            self.ingress_shed += c.get("ingress_shed") or 0
            if c.get("ingress_p50") is not None:
                self.ingress_p50s.append(c["ingress_p50"])
            if c.get("ingress_p99") is not None:
                self.ingress_p99s.append(c["ingress_p99"])

        # --- node logs ---
        self.proposals: dict[str, float] = {}  # block digest -> earliest created
        self.commits: dict[str, float] = {}  # block digest -> earliest commit
        self.committed_payloads: dict[str, tuple[str, float]] = {}  # payload -> (block, t)
        self.payload_sizes: dict[str, int] = {}
        self.sample_to_payload: dict[int, str] = {}
        self.verif_batches: list[tuple[float, int]] = []  # (t, batch size)
        self.timeouts = 0
        self.workload_shed = 0
        self.watchdog_fired: list[str] = []  # anomaly reasons across nodes
        self.watchdog_dumps: list[str] = []  # recorder dump paths
        self.slo_fired: list[str] = []  # SLO burn alerts across nodes
        self.slo_cleared: list[str] = []
        # Incident-ledger fold (one summary line per ledger build): counts
        # sum across logs that carried one, worst MTTR takes the max, and
        # the burn verdict is 'violated' if ANY log said violated.
        self.incident_count = 0
        self.incident_attributed = 0
        self.incident_unattributed = 0
        self.incident_residual = 0
        self.incident_worst_mttr_ms = 0.0
        self.incident_ledgers = 0
        self.burn_verdict: str | None = None
        self.burn_over = 0
        # (epoch, activation round) per switch line across nodes, and the
        # per-range-sync start lags / fetched-block totals (catch-up).
        self.epoch_switches: list[tuple[int, int]] = []
        # (epoch, trigger round, boundary, slack) per committed handoff
        # across nodes, and the count of handoff VIOLATION lines (the
        # epoch-final hard invariant — must stay zero).
        self.handoffs: list[tuple[int, int, int, int]] = []
        self.handoff_violations = 0
        self.range_lags: list[int] = []
        self.range_blocks = 0
        # Aggregation-overlay scrapes: (kind, round, entries) per bundle
        # quorum and (round, entries, peers) per gossip fallback.
        self.agg_quorums: list[tuple[str, int, int]] = []
        self.agg_fallbacks: list[tuple[int, int, int]] = []
        # Certificate-plane fold (cumulative per-node lines): counts sum
        # across nodes; worst bytes / aggregation depth take the max.
        self.cert_agg = 0
        self.cert_legacy = 0
        self.cert_worst_bytes = 0
        self.cert_depth = 0
        self.cert_nodes = 0
        # Proof-plane fold (cumulative per-node lines, like the cert
        # plane): served/subscription/shed counts sum across nodes; the
        # worst proof's wire bytes take the max.
        self.proof_served = 0
        self.proof_subs = 0
        self.proof_shed = 0
        self.proof_worst_bytes = 0
        self.proof_nodes = 0
        # Election-plane fold (cumulative per-node lines, like the cert
        # plane): counts sum across nodes, with the contributing node
        # count kept so per-commit rates stay honest.
        self.elect_rounds = 0
        self.elect_matches = 0
        self.elect_hops = 0
        self.elect_hops_blind = 0
        self.elect_nodes = 0
        # Network-observatory scrapes: (peers, classes, worst EWMA ms) per
        # node that logged an RTT map, plus fleet probe send/answer totals.
        self.peer_rtts: list[tuple[int, int, float]] = []
        self.probes_sent = 0
        self.probes_answered = 0
        # Scenario-matrix lines: (cell, green|red) verdicts, newly-red
        # cell names, and (cell, pct) worst commit-rate deltas.
        self.matrix_cells: list[tuple[str, str]] = []
        self.matrix_regressions: list[str] = []
        self.matrix_worst: list[tuple[str, float]] = []
        # (occupancy %, overlap headroom %) per node that logged telemetry
        self.occupancies: list[tuple[float, float]] = []
        # Worst graftlint finding count across nodes; None when no node
        # log carried the summary line.
        self.graftlint_findings: int | None = None
        # Final METRICS snapshot per node (utils/metrics.py), and the
        # cross-node aggregate (counters summed, histogram count/sum summed).
        self.node_metrics: list[dict] = []
        self.configs = self._parse_configs(nodes[0] if nodes else "")
        for r in _map_logs(_parse_node, nodes):
            for digest, t in r["proposals"].items():
                self.proposals[digest] = min(self.proposals.get(digest, t), t)
            for digest, t in r["commits"].items():
                self.commits[digest] = min(self.commits.get(digest, t), t)
            for payload, (digest, t) in r["committed_payloads"].items():
                prev = self.committed_payloads.get(payload)
                if prev is None or t < prev[1]:
                    self.committed_payloads[payload] = (digest, t)
            self.payload_sizes.update(r["payload_sizes"])
            # Client index is unknown from node logs; samples are joined
            # per-id against every client that sent that id (logs.py:102).
            self.sample_to_payload.update(r["sample_to_payload"])
            self.verif_batches.extend(r["verif_batches"])
            self.timeouts += r["timeouts"]
            self.workload_shed += r["workload_shed"]
            self.watchdog_fired.extend(r.get("watchdog_fired", []))
            self.watchdog_dumps.extend(r.get("watchdog_dumps", []))
            self.slo_fired.extend(r.get("slo_fired", []))
            self.slo_cleared.extend(r.get("slo_cleared", []))
            if r.get("incident_ledger") is not None:
                n_inc, att, unatt, resid, worst = r["incident_ledger"]
                self.incident_count += n_inc
                self.incident_attributed += att
                self.incident_unattributed += unatt
                self.incident_residual += resid
                self.incident_worst_mttr_ms = max(
                    self.incident_worst_mttr_ms, worst
                )
                self.incident_ledgers += 1
            if r.get("burn_verdict") is not None:
                verdict, over = r["burn_verdict"]
                self.burn_over += over
                if self.burn_verdict != "violated":
                    self.burn_verdict = verdict
            self.epoch_switches.extend(r.get("epoch_switches", []))
            self.handoffs.extend(r.get("handoffs", []))
            self.handoff_violations += r.get("handoff_violations", 0)
            self.range_lags.extend(r.get("range_lags", []))
            self.range_blocks += r.get("range_blocks", 0)
            self.agg_quorums.extend(r.get("agg_quorums", []))
            self.agg_fallbacks.extend(r.get("agg_fallbacks", []))
            if r.get("cert_plane") is not None:
                n_agg, n_legacy, worst_b, depth = r["cert_plane"]
                self.cert_agg += n_agg
                self.cert_legacy += n_legacy
                self.cert_worst_bytes = max(self.cert_worst_bytes, worst_b)
                self.cert_depth = max(self.cert_depth, depth)
                self.cert_nodes += 1
            if r.get("proof_plane") is not None:
                p_served, p_subs, p_shed, p_worst = r["proof_plane"]
                self.proof_served += p_served
                self.proof_subs += p_subs
                self.proof_shed += p_shed
                self.proof_worst_bytes = max(self.proof_worst_bytes, p_worst)
                self.proof_nodes += 1
            if r.get("election") is not None:
                e_rounds, e_matches, e_hops, e_blind = r["election"]
                self.elect_rounds += e_rounds
                self.elect_matches += e_matches
                self.elect_hops += e_hops
                self.elect_hops_blind += e_blind
                self.elect_nodes += 1
            if r.get("peer_rtt") is not None:
                self.peer_rtts.append(r["peer_rtt"])
            if r.get("probes") is not None:
                self.probes_sent += r["probes"][0]
                self.probes_answered += r["probes"][1]
            self.matrix_cells.extend(r.get("matrix_cells", []))
            self.matrix_regressions.extend(r.get("matrix_regressions", []))
            self.matrix_worst.extend(r.get("matrix_worst", []))
            if r.get("occupancy") is not None:
                self.occupancies.append(r["occupancy"])
            if r.get("graftlint_findings") is not None:
                self.graftlint_findings = (
                    r["graftlint_findings"]
                    if self.graftlint_findings is None
                    else max(self.graftlint_findings, r["graftlint_findings"])
                )
            if r.get("metrics") is not None:
                self.node_metrics.append(r["metrics"])
        self.metrics = self._merge_metrics(self.node_metrics)

    @staticmethod
    def _merge_metrics(snapshots: list[dict]) -> dict:
        """Aggregate per-node snapshots: counters sum; histograms keep the
        summed count/sum (mean re-derived) and the max of max — percentiles
        are not mergeable across nodes and are dropped. Snapshots missing
        keys or carrying junk values are tolerated (scraped from logs)."""
        counters: dict[str, int] = {}
        histograms: dict[str, dict] = {}
        for snap in snapshots:
            for name, v in (snap.get("counters") or {}).items():
                if isinstance(v, (int, float)):
                    counters[name] = counters.get(name, 0) + v
            for name, h in (snap.get("histograms") or {}).items():
                if not isinstance(h, dict):
                    continue
                agg = histograms.setdefault(
                    name, {"count": 0, "sum": 0.0, "max": 0.0}
                )
                if isinstance(h.get("count"), (int, float)):
                    agg["count"] += h["count"]
                if isinstance(h.get("sum"), (int, float)):
                    agg["sum"] += h["sum"]
                if isinstance(h.get("max"), (int, float)):
                    agg["max"] = max(agg["max"], h["max"])
        return {"counters": counters, "histograms": histograms}

    @staticmethod
    def _parse_configs(text: str) -> dict:
        out = {}
        for pat, key in [
            (r"Timeout delay set to (\d+) ms", "timeout_delay"),
            (r"Sync retry delay set to (\d+) ms", "sync_retry_delay"),
            (r"Max payload size set to (\d+) B", "max_payload_size"),
            (r"Min block delay set to (\d+) ms", "min_block_delay"),
            (r"Queue capacity set to (\d+)", "queue_capacity"),
            (r"Probe interval set to (\d+) ms", "probe_interval"),
        ]:
            ms = re.findall(pat, text)
            if ms:
                out[key] = int(ms[0])
        return out

    # --- metrics (reference logs.py:149-182) ---

    # Boot skew below this is treated as synchronized-start (reference
    # semantics): genuine interpreter-boot skew on an oversubscribed host is
    # tens of seconds, while cross-machine NTP drift on a remote run is
    # sub-second — the threshold keeps the latter from shifting the window.
    _SKEW_THRESHOLD_S = 5.0

    def _steady_window_start(self) -> float | None:
        if self.start is None or self.steady_start is None:
            return self.start
        if self.steady_start - self.start > self._SKEW_THRESHOLD_S:
            return self.steady_start
        return self.start

    def _windowed_throughput(self, start: float) -> tuple[float, float, float]:
        """(TPS, BPS, duration) over [start, last commit]: only payloads
        committed inside the window count, so a ramp period excluded from
        the denominator is excluded from the numerator too. (Residual known
        bias: transactions QUEUED during the ramp but committed just after
        it drain as in-window commits — the readiness gate in
        benchmark/local.py keeps that backlog small by not starting the
        measured duration until every client is sending.)"""
        end = max(self.commits.values())
        duration = max(end - start, 1e-9)
        bytes_total = sum(
            self.payload_sizes.get(p, 0)
            for p, (_digest, t) in self.committed_payloads.items()
            if t >= start
        )
        bps = bytes_total / duration
        tps = bps / self.size if self.size else 0.0
        return tps, bps, duration

    def consensus_throughput(self) -> tuple[float, float, float]:
        """(TPS, BPS, duration). Bytes = sizes of committed payloads.
        The window opens at the first proposal, clamped to the
        steady-state start (see `steady_start`) so client boot skew on an
        oversubscribed host doesn't dilute the rate."""
        if not self.commits:
            return 0.0, 0.0, 0.0
        start = min(self.proposals.values()) if self.proposals else min(self.commits.values())
        steady = self._steady_window_start()
        if steady is not None:
            start = max(start, steady)
        return self._windowed_throughput(start)

    def consensus_latency(self) -> float:
        """Mean propose->commit time over blocks PROPOSED inside the
        steady-state window (ramp-period blocks ran against partial load
        and would bias the mean low)."""
        steady = self._steady_window_start() or 0.0
        lat = [
            self.commits[d] - self.proposals[d]
            for d in self.commits
            if d in self.proposals and self.proposals[d] >= steady
        ]
        return mean(lat) if lat else 0.0

    def end_to_end_throughput(self) -> tuple[float, float, float]:
        """Window opens when the LAST client starts sending (equals the
        first on real hardware; excludes the boot-skew ramp on an
        oversubscribed host)."""
        if not self.commits or self.start is None:
            return 0.0, 0.0, 0.0
        return self._windowed_throughput(self._steady_window_start())

    def end_to_end_latency(self) -> float:
        """Mean send->commit time over samples SENT inside the steady-state
        window (a ramp-period sample measures an uncontended system)."""
        steady = self._steady_window_start() or 0.0
        lat = []
        for (client, sid), sent in self.sent_samples.items():
            if sent < steady:
                continue
            payload = self.sample_to_payload.get(sid)
            if payload is None:
                continue
            hit = self.committed_payloads.get(payload)
            if hit is None:
                continue
            lat.append(hit[1] - sent)
        return mean(lat) if lat else 0.0

    def verification_throughput(self) -> tuple[float, int]:
        """(verified signatures/sec across the run, total verified) from the
        fork's batch log lines -- the votes-verified/sec metric."""
        if not self.verif_batches:
            return 0.0, 0
        times = [t for t, _ in self.verif_batches]
        total = sum(n for _, n in self.verif_batches)
        duration = max(max(times) - min(times), 1e-9)
        return total / duration, total

    def result(self) -> str:
        c_tps, c_bps, _ = self.consensus_throughput()
        c_lat = self.consensus_latency()
        e_tps, e_bps, _ = self.end_to_end_throughput()
        e_lat = self.end_to_end_latency()
        v_rate, v_total = self.verification_throughput()
        ingress = ""
        if self.ingress_offered:
            shed_pct = 100.0 * self.ingress_shed / self.ingress_offered
            p50 = mean(self.ingress_p50s) if self.ingress_p50s else 0.0
            p99 = max(self.ingress_p99s) if self.ingress_p99s else 0.0
            ingress = (
                " + INGRESS:\n"
                f" Offered: {self.ingress_offered:,} tx"
                f" ({self.ingress_accepted:,} accepted,"
                f" {self.ingress_shed:,} shed = {shed_pct:.1f} %)\n"
                f" Client latency p50 (mean across clients): {p50:,.1f} ms\n"
                f" Client latency p99 (worst client): {p99:,.1f} ms\n"
            )
        mtr = ""
        if self.metrics["counters"] or self.metrics["histograms"]:
            lines = [
                f" {name}: {value:,}"
                for name, value in sorted(self.metrics["counters"].items())
                if value
            ]
            # h_mean, NOT `mean`: that name is statistics.mean at module
            # scope, and shadowing it here made the whole function treat
            # the import as unbound (the PR 6 hand-computed-mean wart).
            for name, h in sorted(self.metrics["histograms"].items()):
                if h["count"]:
                    h_mean = h["sum"] / h["count"]
                    lines.append(
                        f" {name}: count={h['count']:,} mean={h_mean:.6g} "
                        f"max={h['max']:.6g}"
                    )
            if lines:
                mtr = (
                    f" + METRICS ({len(self.node_metrics)} node snapshots):\n"
                    + "\n".join(lines)
                    + "\n"
                )
        network = ""
        if self.peer_rtts or self.probes_sent:
            network = " + NETWORK:\n"
            if self.peer_rtts:
                # Worst link anywhere in the fleet; the class count from
                # the same vantage says whether that link crossed an RTT
                # class boundary (>= 2 classes: a cross-region hop).
                peers, classes, worst = max(
                    self.peer_rtts, key=lambda pcw: pcw[2]
                )
                network += (
                    f" Worst peer RTT EWMA: {worst:,.1f} ms"
                    f" ({peers} peer(s) in {classes} RTT class(es)"
                    " from that vantage)\n"
                )
            if self.probes_sent:
                lost = max(0, self.probes_sent - self.probes_answered)
                loss_pct = 100.0 * lost / self.probes_sent
                network += (
                    f" Probes: {self.probes_sent:,} sent,"
                    f" {self.probes_answered:,} answered"
                    f" ({lost:,} outstanding = {loss_pct:.1f} %)\n"
                )
        telemetry = ""
        if self.occupancies or self.slo_fired or self.slo_cleared:
            telemetry = " + TELEMETRY:\n"
            if self.occupancies:
                # Worst node = LOWEST device occupancy (the node whose
                # device sat idle the most is the one gap attribution
                # should start from).
                worst = min(self.occupancies, key=lambda oc: oc[0])
                telemetry += (
                    f" Worst-node device occupancy: {worst[0]:.1f} %"
                    f" (overlap headroom {worst[1]:.1f} %)\n"
                )
            if self.slo_fired or self.slo_cleared:
                names = ", ".join(sorted(set(self.slo_fired))) or "-"
                telemetry += (
                    f" SLO burn alerts: {len(self.slo_fired)} fired"
                    f" ({names}), {len(self.slo_cleared)} cleared\n"
                )
        incidents = ""
        if self.incident_ledgers:
            incidents = (
                " + INCIDENTS:\n"
                f" Incidents: {self.incident_count}"
                f" ({self.incident_attributed} alert(s) attributed,"
                f" {self.incident_unattributed} unattributed,"
                f" {self.incident_residual} residual)\n"
                f" Worst MTTR: {self.incident_worst_mttr_ms:,.1f} ms\n"
            )
            if self.burn_verdict is not None:
                incidents += (
                    f" Burn budget: {self.burn_verdict}"
                    f" ({self.burn_over} SLO row(s) over)\n"
                )
        matrix = ""
        if self.matrix_cells:
            greens = sum(1 for _c, v in self.matrix_cells if v == "green")
            reds = len(self.matrix_cells) - greens
            matrix = (
                " + MATRIX:\n"
                f" Cells: {len(self.matrix_cells)} run"
                f" ({greens} green, {reds} red)\n"
            )
            if self.matrix_regressions:
                names = ", ".join(sorted(set(self.matrix_regressions)))
                matrix += (
                    f" REGRESSION: {len(self.matrix_regressions)} previously-"
                    f"green cell(s) went red: {names}\n"
                )
            if self.matrix_worst:
                cell, pct = min(self.matrix_worst, key=lambda cw: cw[1])
                matrix += (
                    f" Worst commit-rate delta vs baseline: {cell}"
                    f" {pct:+.2f} %\n"
                )
        agg = ""
        if self.agg_quorums or self.agg_fallbacks:
            agg = " + AGG:\n"
            if self.agg_quorums:
                qcs = sum(1 for k, _r, _n in self.agg_quorums if k == "QC")
                tcs = len(self.agg_quorums) - qcs
                entries = sum(n for _k, _r, n in self.agg_quorums)
                agg += (
                    f" Bundle quorums: {len(self.agg_quorums)}"
                    f" ({qcs} QC, {tcs} TC) from {entries:,} merged entries\n"
                )
            if self.agg_fallbacks:
                gossiped = sum(e for _r, e, _p in self.agg_fallbacks)
                frames = sum(p for _r, _e, p in self.agg_fallbacks)
                agg += (
                    f" Fallbacks: {len(self.agg_fallbacks)}"
                    f" ({gossiped:,} entries gossiped over {frames:,} frames)\n"
                )
        certs = ""
        if self.cert_nodes:
            total_certs = self.cert_agg + self.cert_legacy
            agg_pct = 100.0 * self.cert_agg / total_certs if total_certs else 0.0
            certs = (
                " + CERTS:\n"
                f" Committed certificates: {total_certs:,}"
                f" ({self.cert_agg:,} aggregate = {agg_pct:.1f} %,"
                f" {self.cert_legacy:,} entry-list)"
                f" across {self.cert_nodes} node(s)\n"
                f" Worst cert: {self.cert_worst_bytes:,} B,"
                f" aggregation depth {self.cert_depth}\n"
            )
        proofs = ""
        if self.proof_nodes:
            shed_pct = (
                100.0 * self.proof_shed / (self.proof_subs + self.proof_shed)
                if (self.proof_subs + self.proof_shed)
                else 0.0
            )
            proofs = (
                " + PROOFS:\n"
                f" Proofs served: {self.proof_served:,}"
                f" across {self.proof_nodes} node(s)"
                f" ({self.proof_subs:,} subscriptions,"
                f" {self.proof_shed:,} shed = {shed_pct:.1f} %)\n"
                f" Worst proof: {self.proof_worst_bytes:,} B\n"
            )
        election = ""
        if self.elect_nodes and self.elect_rounds:
            match_pct = 100.0 * self.elect_matches / self.elect_rounds
            hops_per = self.elect_hops / self.elect_rounds
            blind_per = self.elect_hops_blind / self.elect_rounds
            election = (
                " + ELECTION:\n"
                f" Pivots scored: {self.elect_rounds:,} committed round(s)"
                f" across {self.elect_nodes} node(s)\n"
                f" Co-located: {self.elect_matches:,} ({match_pct:.1f} %);"
                f" cross-region hops: {self.elect_hops:,}"
                f" ({hops_per:.3f}/commit vs {blind_per:.3f} under"
                " round-robin)\n"
            )
        reconfig = ""
        if self.epoch_switches or self.handoffs or self.range_lags:
            reconfig = " + RECONFIG:\n"
            if self.epoch_switches:
                top_epoch, top_round = max(self.epoch_switches)
                reconfig += (
                    f" Epoch switches observed: {len(self.epoch_switches)}"
                    f" (highest epoch {top_epoch} at round {top_round})\n"
                )
            if self.handoffs:
                rotations = len({e for e, _t, _b, _s in self.handoffs})
                # worst = SMALLEST slack: the handoff that came closest
                # to its boundary (the margin-sizing signal).
                worst_slack = min(s for _e, _t, _b, s in self.handoffs)
                reconfig += (
                    f" Handoffs: {len(self.handoffs)} across"
                    f" {rotations} rotation(s), worst slack"
                    f" {worst_slack} round(s) before the boundary\n"
                )
            if self.range_lags:
                reconfig += (
                    f" Catch-up: {len(self.range_lags)} range sync(s),"
                    f" worst start lag {max(self.range_lags)} rounds,"
                    f" {self.range_blocks} blocks fetched\n"
                )
        lint = ""
        if self.graftlint_findings is not None:
            lint = (
                " + LINT:\n"
                f" graftlint: {self.graftlint_findings} findings\n"
            )
        warn = ""
        if self.graftlint_findings:
            warn += (
                f" WARNING: graftlint reported {self.graftlint_findings} "
                "finding(s) — the deployed tree violates committed "
                "contracts\n"
            )
        if self.handoff_violations:
            warn += (
                f" WARNING: {self.handoff_violations} epoch handoff "
                "VIOLATION(s) — a commit landed at/past its declared "
                "activation round (the epoch-final invariant; gap rounds "
                "were certified by the old committee)\n"
            )
        if self.incident_unattributed or self.burn_verdict == "violated":
            warn += (
                f" WARNING: incident ledger left "
                f"{self.incident_unattributed} alert(s) unattributed and "
                f"judged the burn budget {self.burn_verdict or 'unjudged'} "
                f"({self.burn_over} SLO row(s) over) — fault attribution "
                "or the error budget broke down\n"
            )
        if self.misses:
            warn += f" WARNING: {self.misses} rate-too-high warnings\n"
        if self.timeouts > 2:
            warn += f" WARNING: {self.timeouts} timeouts\n"
        if self.watchdog_fired:
            reasons = ", ".join(sorted(set(self.watchdog_fired)))
            warn += (
                f" WARNING: anomaly watchdog fired {len(self.watchdog_fired)}x"
                f" ({reasons}); {len(self.watchdog_dumps)} recorder dump(s)"
                " written — read them before trusting these numbers\n"
            )
        return (
            "\n-----------------------------------------\n"
            " SUMMARY:\n"
            "-----------------------------------------\n"
            " + CONFIG:\n"
            f" Committee size: {self.committee_size} nodes\n"
            f" Faults: {self.faults} nodes\n"
            f" Input rate: {self.rate:,} tx/s\n"
            f" Transaction size: {self.size:,} B\n"
            f" {self.configs}\n"
            f"{warn}"
            " + RESULTS:\n"
            f" Consensus TPS: {round(c_tps):,} tx/s\n"
            f" Consensus BPS: {round(c_bps):,} B/s\n"
            f" Consensus latency: {round(c_lat * 1000):,} ms\n"
            f" End-to-end TPS: {round(e_tps):,} tx/s\n"
            f" End-to-end BPS: {round(e_bps):,} B/s\n"
            f" End-to-end latency: {round(e_lat * 1000):,} ms\n"
            f" Batch verification rate: {round(v_rate):,} sigs/s ({v_total:,} total)\n"
            + (
                f" Workload shed at saturation: >= {self.workload_shed:,} sigs\n"
                if self.workload_shed
                else ""
            )
            + ingress
            + network
            + telemetry
            + incidents
            + lint
            + matrix
            + agg
            + certs
            + proofs
            + election
            + reconfig
            + mtr
            + "-----------------------------------------\n"
        )

    @classmethod
    def process(cls, directory: str, faults: int = 0) -> "LogParser":
        clients = []
        for path in sorted(glob(join(directory, "client-*.log"))):
            with open(path) as f:
                clients.append(f.read())
        nodes = []
        for path in sorted(glob(join(directory, "node-*.log"))):
            with open(path) as f:
                nodes.append(f.read())
        return cls(clients, nodes, faults)
