"""Benchmark configuration builders (reference benchmark/benchmark/config.py:21-166).

Builds the committee/parameters JSON files the node binary consumes, with the
LocalCommittee port layout: consensus base+i, mempool base+size+i, front
base+2*size+i (config.py:101-112).
"""

from __future__ import annotations

import json


class ConfigError(Exception):
    pass


class Key:
    def __init__(self, name: str, secret: str) -> None:
        self.name = name
        self.secret = secret

    @classmethod
    def from_file(cls, filename: str) -> "Key":
        with open(filename) as f:
            data = json.load(f)
        return cls(data["name"], data["secret"])


class BenchParameters:
    """Validated benchmark sweep parameters (config.py:118-146)."""

    def __init__(self, obj: dict) -> None:
        try:
            nodes = obj["nodes"]
            nodes = nodes if isinstance(nodes, list) else [nodes]
            rate = obj["rate"]
            rate = rate if isinstance(rate, list) else [rate]
            self.nodes = [int(x) for x in nodes]
            self.rate = [int(x) for x in rate]
            self.tx_size = int(obj["tx_size"])
            self.faults = int(obj.get("faults", 0))
            self.duration = int(obj["duration"])
            self.runs = int(obj.get("runs", 1))
        except (KeyError, ValueError, TypeError) as e:
            raise ConfigError(f"malformed bench parameters: {e}") from e
        if min(self.nodes) <= 1 or min(self.rate) < 0 or self.tx_size < 9:
            raise ConfigError("invalid bench parameter values")


class NodeParameters:
    """Validates and writes node parameter files (config.py:148-166)."""

    def __init__(self, obj: dict) -> None:
        self.obj = {"consensus": obj.get("consensus", {}), "mempool": obj.get("mempool", {})}

    def write(self, filename: str) -> None:
        with open(filename, "w") as f:
            json.dump(self.obj, f, indent=2, sort_keys=True)


class LocalCommittee:
    """Committee JSON for a localhost testbed (config.py:101-112)."""

    def __init__(self, names: list[str], port: int) -> None:
        self.names = names
        self.port = port
        size = len(names)
        self.consensus_addr = {
            n: f"127.0.0.1:{port + i}" for i, n in enumerate(names)
        }
        self.mempool_addr = {
            n: f"127.0.0.1:{port + size + i}" for i, n in enumerate(names)
        }
        self.front_addr = {
            n: f"127.0.0.1:{port + 2 * size + i}" for i, n in enumerate(names)
        }

    def to_json(self) -> dict:
        return {
            "consensus": {
                "epoch": 1,
                "authorities": {
                    n: {"stake": 1, "address": self.consensus_addr[n]}
                    for n in self.names
                },
            },
            "mempool": {
                "epoch": 1,
                "authorities": {
                    n: {
                        "front_address": self.front_addr[n],
                        "mempool_address": self.mempool_addr[n],
                    }
                    for n in self.names
                },
            },
        }

    def write(self, filename: str) -> None:
        with open(filename, "w") as f:
            json.dump(self.to_json(), f, indent=2, sort_keys=True)
