"""Local benchmark runner (reference benchmark/benchmark/local.py:37-120).

Boots a committee of node processes plus one client per node on localhost,
runs for `duration` seconds, kills everything, and parses the logs. The
reference manages processes with tmux; here plain subprocesses with per-process
log redirection (logs/node-i.log, logs/client-i.log) serve the same role.
"""

from __future__ import annotations

import os
import shlex
import signal
import subprocess
import time
from os.path import join

from .commands import CommandMaker
from .config import BenchParameters, LocalCommittee, NodeParameters
from .logs import LogParser, ParseError


class BenchError(Exception):
    pass


class LocalBench:
    BASE_PORT = 9_000

    def __init__(self, bench_params: dict, node_params: dict) -> None:
        self.bench = BenchParameters(bench_params)
        self.node_params = NodeParameters(node_params)
        self.crypto = bench_params.get("crypto", "cpu")
        # Sidecar pipeline chunk override (device chunk sweep's verdict);
        # None = verifier default.
        self.sidecar_chunk = bench_params.get("sidecar_chunk")
        self._procs: list[subprocess.Popen] = []

    def _background_run(self, command: str, log_file: str) -> subprocess.Popen:
        with open(log_file, "w") as out:
            proc = subprocess.Popen(
                shlex.split(command),
                stdout=out,
                stderr=subprocess.STDOUT,
                cwd=os.getcwd(),
                start_new_session=True,
            )
        self._procs.append(proc)
        return proc

    @staticmethod
    def _await_in_logs(waits, phrase: str, timeout: float, what: str) -> None:
        """Block until every (log_path, proc) in `waits` has `phrase` in its
        log. Fails fast with the real exit code when a process dies during
        startup instead of burning the timeout on a log line that can never
        appear."""
        deadline = time.monotonic() + timeout
        pending = dict(waits)
        while pending and time.monotonic() < deadline:
            time.sleep(0.5)
            for path, proc in list(pending.items()):
                if proc.poll() is not None:
                    raise BenchError(
                        f"{what} exited at startup "
                        f"(rc={proc.returncode}); see {path}"
                    )
                try:
                    with open(path) as f:
                        if phrase in f.read():
                            del pending[path]
                except OSError:
                    pass
        if pending:
            raise BenchError(f"{what} never ready: {sorted(pending)}")

    def _kill(self) -> None:
        for proc in self._procs:
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
        self._procs.clear()

    def run(self, debug: bool = False) -> LogParser:
        nodes = self.bench.nodes[0]
        rate = self.bench.rate[0]
        faults = self.bench.faults
        boot = nodes - faults

        print(f"Running local benchmark: {nodes} nodes ({faults} faults), "
              f"{rate} tx/s, {self.bench.tx_size} B txs, {self.bench.duration} s, "
              f"crypto={self.crypto}")
        subprocess.run(CommandMaker.kill(), shell=True, capture_output=True)
        subprocess.run(CommandMaker.cleanup(), shell=True, check=True)
        subprocess.run(CommandMaker.clean_logs(), shell=True, check=True)

        try:
            # Generate keys and committee (in-process: one interpreter launch
            # per key is prohibitively slow on small boxes).
            from hotstuff_tpu.node.config import Secret

            key_files = [f".node-{i}.json" for i in range(nodes)]
            names = []
            for f in key_files:
                secret = Secret.new()
                secret.write(f)
                names.append(secret.name.encode_base64())
            committee = LocalCommittee(names, self.BASE_PORT)
            committee.write(".committee.json")
            self.node_params.write(".parameters.json")

            # TPU crypto: boot ONE sidecar process owning the chip; nodes
            # connect as remote clients (the TPU is process-exclusive).
            node_crypto, crypto_addr = self.crypto, None
            if self.crypto == "tpu":
                sidecar_port = self.BASE_PORT - 100
                crypto_addr = f"127.0.0.1:{sidecar_port}"
                sidecar_proc = self._background_run(
                    CommandMaker.run_sidecar(
                        sidecar_port,
                        "tpu",
                        debug=debug,
                        chunk=self.sidecar_chunk,
                        committee=".committee.json",
                    ),
                    join("logs", "sidecar.log"),
                )
                # JAX/TPU init + per-bucket warmup (even cache-hits pay
                # ~30 s device program load over a tunneled chip)
                self._await_in_logs(
                    [(join("logs", "sidecar.log"), sidecar_proc)],
                    "successfully booted",
                    480,
                    "crypto sidecar",
                )
                node_crypto = "remote"

            # Boot nodes (skipping `faults` of them -- fault injection by
            # simply not booting, local.py:75-76).
            node_waits = []
            for i in range(boot):
                cmd = CommandMaker.run_node(
                    key_files[i],
                    ".committee.json",
                    f".db-{i}/log",
                    ".parameters.json",
                    crypto=node_crypto,
                    crypto_addr=crypto_addr,
                    debug=debug,
                )
                log_path = CommandMaker.logs_path("logs", "node", i)
                node_waits.append((log_path, self._background_run(cmd, log_path)))

            # Wait until every node reports booted: Python interpreter
            # startup under CPU contention can take ~10 s on small machines,
            # and killing before boot would measure nothing. The timeout
            # scales with committee size (2n processes share one core).
            self._await_in_logs(
                node_waits, "successfully booted", 90 + 6 * boot, "node"
            )

            # One client per booted node.
            per_client_rate = max(1, rate // boot)
            consensus_addrs = [
                committee.consensus_addr[n] for n in names[:boot]
            ]
            client_waits = []
            for i in range(boot):
                cmd = CommandMaker.run_client(
                    committee.front_addr[names[i]],
                    self.bench.tx_size,
                    per_client_rate,
                    consensus_addrs,
                )
                log_path = CommandMaker.logs_path("logs", "client", i)
                client_waits.append(
                    (log_path, self._background_run(cmd, log_path))
                )

            # Wait until every client is actually sending before starting
            # the measurement clock: at 2 processes per node on one core,
            # the last client interpreters can take >60 s to start (at
            # n=20 the entire 60 s window used to elapse with zero
            # transactions sent — blocks committed empty and the run
            # parsed as a zero-TPS "cliff" that was purely boot skew).
            # LogParser additionally starts its steady-state window at the
            # LAST client's first send, so any residual skew stays out of
            # the throughput denominator.
            self._await_in_logs(
                client_waits,
                "Start sending transactions",
                90 + 6 * boot,
                "client",
            )

            time.sleep(self.bench.duration)
            self._kill()
            time.sleep(0.5)
            return LogParser.process("logs", faults)
        except (subprocess.SubprocessError, ParseError, OSError) as e:
            self._kill()
            raise BenchError(f"local benchmark failed: {e}") from e
        finally:
            self._kill()
