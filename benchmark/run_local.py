"""CLI driver for the local benchmark (Fabric-free `fab local`).

    python -m benchmark.run_local --nodes 4 --rate 1000 --size 512 \
        --duration 20 [--faults 0] [--crypto cpu|tpu]
"""

from __future__ import annotations

import argparse

from .fabfile import LOCAL_NODE_PARAMS
from .local import LocalBench


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument("--rate", type=int, default=1_000)
    p.add_argument("--size", type=int, default=512)
    p.add_argument("--faults", type=int, default=0)
    p.add_argument("--duration", type=int, default=20)
    p.add_argument("--crypto", default="cpu", choices=["cpu", "tpu"])
    p.add_argument("--benchmark-workload", action="store_true",
                   help="enable the fork's synthetic batch-verification workload")
    p.add_argument("--mempool-payload-size", type=int, default=None,
                   help="override mempool max_payload_size (bytes); bigger "
                   "payloads = bigger verification batches (reference remote "
                   "config uses 500 kB, fabfile.py:107-120)")
    p.add_argument("--timeout-delay", type=int, default=None,
                   help="override consensus timeout_delay (ms)")
    p.add_argument("--sidecar-chunk", type=int, default=None,
                   help="TPU sidecar upload-pipeline chunk size (the device "
                   "chunk sweep's verdict); only with --crypto tpu")
    p.add_argument("--debug", action="store_true")
    args = p.parse_args()
    if args.sidecar_chunk is not None and args.crypto != "tpu":
        p.error("--sidecar-chunk requires --crypto tpu")
    if args.sidecar_chunk is not None and args.sidecar_chunk <= 0:
        # mirror the sidecar CLI's own check; failing here beats a
        # mid-benchmark "sidecar exited at startup"
        p.error("--sidecar-chunk must be positive")

    bench_params = {
        "nodes": args.nodes,
        "rate": args.rate,
        "tx_size": args.size,
        "faults": args.faults,
        "duration": args.duration,
        "crypto": args.crypto,
        "sidecar_chunk": args.sidecar_chunk,
    }
    node_params = {k: dict(v) for k, v in LOCAL_NODE_PARAMS.items()}
    if args.benchmark_workload:
        node_params["mempool"]["benchmark_mode"] = True
    if args.mempool_payload_size is not None:
        node_params["mempool"]["max_payload_size"] = args.mempool_payload_size
    if args.timeout_delay is not None:
        node_params["consensus"]["timeout_delay"] = args.timeout_delay
    parser = LocalBench(bench_params, node_params).run(debug=args.debug)
    print(parser.result())


if __name__ == "__main__":
    main()
