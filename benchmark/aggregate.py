"""Result aggregation (reference benchmark/benchmark/aggregate.py:75-174).

Groups result .txt files by setup (nodes, faults, tx size), averages repeated
runs, and emits agg-*.txt files consumable by plot.py.
"""

from __future__ import annotations

import re
from collections import defaultdict
from glob import glob
from os.path import join
from statistics import mean, stdev


def _extract(text: str, pattern: str) -> float | None:
    m = re.search(pattern, text)
    return float(m.group(1).replace(",", "")) if m else None


def parse_result_file(path: str) -> dict:
    with open(path) as f:
        text = f.read()
    return {
        "nodes": _extract(text, r"Committee size: ([\d,]+)"),
        "faults": _extract(text, r"Faults: ([\d,]+)"),
        "rate": _extract(text, r"Input rate: ([\d,]+)"),
        "tx_size": _extract(text, r"Transaction size: ([\d,]+)"),
        "consensus_tps": _extract(text, r"Consensus TPS: ([\d,]+)"),
        "consensus_latency": _extract(text, r"Consensus latency: ([\d,]+)"),
        "e2e_tps": _extract(text, r"End-to-end TPS: ([\d,]+)"),
        "e2e_latency": _extract(text, r"End-to-end latency: ([\d,]+)"),
    }


def aggregate_results(directory: str = "results") -> dict:
    """Means/stdevs per (nodes, faults, tx_size, rate) setup."""
    groups: dict[tuple, list[dict]] = defaultdict(list)
    for path in sorted(glob(join(directory, "bench-*.txt"))):
        r = parse_result_file(path)
        key = (r["nodes"], r["faults"], r["tx_size"], r["rate"])
        groups[key].append(r)

    out = {}
    for key, runs in sorted(groups.items()):
        agg = {}
        for metric in ("consensus_tps", "consensus_latency", "e2e_tps", "e2e_latency"):
            vals = [r[metric] for r in runs if r[metric] is not None]
            agg[metric] = {
                "mean": mean(vals) if vals else 0.0,
                "stdev": stdev(vals) if len(vals) > 1 else 0.0,
                "runs": len(vals),
            }
        out[key] = agg

    lines = ["setup(nodes,faults,tx_size,rate) -> metric: mean ± stdev (runs)"]
    for key, agg in out.items():
        for metric, v in agg.items():
            lines.append(
                f"{key} {metric}: {v['mean']:.0f} ± {v['stdev']:.0f} ({v['runs']})"
            )
    with open(join(directory, "aggregated.txt"), "w") as f:
        f.write("\n".join(lines) + "\n")
    return out
