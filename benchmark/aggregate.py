"""Result aggregation (reference benchmark/benchmark/aggregate.py:75-174).

Groups result .txt files by setup (nodes, faults, tx size), averages repeated
runs, and emits agg-*.txt files consumable by plot.py.
"""

from __future__ import annotations

import re
from collections import defaultdict
from glob import glob
from os.path import join
from statistics import mean, stdev


def _extract(text: str, pattern: str) -> float | None:
    m = re.search(pattern, text)
    return float(m.group(1).replace(",", "")) if m else None


def parse_result_file(path: str) -> dict:
    with open(path) as f:
        text = f.read()
    return {
        "nodes": _extract(text, r"Committee size: ([\d,]+)"),
        "faults": _extract(text, r"Faults: ([\d,]+)"),
        "rate": _extract(text, r"Input rate: ([\d,]+)"),
        "tx_size": _extract(text, r"Transaction size: ([\d,]+)"),
        "consensus_tps": _extract(text, r"Consensus TPS: ([\d,]+)"),
        "consensus_latency": _extract(text, r"Consensus latency: ([\d,]+)"),
        "e2e_tps": _extract(text, r"End-to-end TPS: ([\d,]+)"),
        "e2e_latency": _extract(text, r"End-to-end latency: ([\d,]+)"),
    }


def aggregate_results(directory: str = "results") -> dict:
    """Means/stdevs per (nodes, faults, tx_size, rate) setup."""
    groups: dict[tuple, list[dict]] = defaultdict(list)
    for path in sorted(glob(join(directory, "bench-*.txt"))):
        r = parse_result_file(path)
        key = (r["nodes"], r["faults"], r["tx_size"], r["rate"])
        groups[key].append(r)

    out = {}
    for key, runs in sorted(groups.items()):
        agg = {}
        for metric in ("consensus_tps", "consensus_latency", "e2e_tps", "e2e_latency"):
            vals = [r[metric] for r in runs if r[metric] is not None]
            agg[metric] = {
                "mean": mean(vals) if vals else 0.0,
                "stdev": stdev(vals) if len(vals) > 1 else 0.0,
                "runs": len(vals),
            }
        out[key] = agg

    lines = ["setup(nodes,faults,tx_size,rate) -> metric: mean ± stdev (runs)"]
    for key, agg in out.items():
        for metric, v in agg.items():
            lines.append(
                f"{key} {metric}: {v['mean']:.0f} ± {v['stdev']:.0f} ({v['runs']})"
            )
    with open(join(directory, "aggregated.txt"), "w") as f:
        f.write("\n".join(lines) + "\n")
    _write_family_files(out, directory)
    return out


def _write_family_files(
    out: dict, directory: str, max_latency_ms=(2_000, 5_000)
) -> None:
    """The reference's per-plot-family agg files (aggregate.py:75-174):
    latency (L-graph points), robustness (tput vs input rate), and best-tps
    under a max-latency SLO per committee size."""
    lat_lines, rob_lines = [], []
    for (nodes, faults, tx_size, rate), agg in out.items():
        tag = f"nodes={nodes:.0f} faults={faults:.0f} tx={tx_size:.0f}"
        lat_lines.append(
            f"{tag} rate={rate:.0f} tps={agg['e2e_tps']['mean']:.0f} "
            f"latency_ms={agg['e2e_latency']['mean']:.0f} "
            f"±{agg['e2e_latency']['stdev']:.0f}"
        )
        rob_lines.append(
            f"{tag} rate={rate:.0f} tps={agg['e2e_tps']['mean']:.0f} "
            f"±{agg['e2e_tps']['stdev']:.0f}"
        )
    with open(join(directory, "agg-latency.txt"), "w") as f:
        f.write("\n".join(lat_lines) + "\n")
    with open(join(directory, "agg-robustness.txt"), "w") as f:
        f.write("\n".join(rob_lines) + "\n")

    tps_lines = []
    for slo in max_latency_ms:
        best = best_tps_under_slo(out, slo)
        for nodes in sorted(best):
            tps_lines.append(
                f"max_latency_ms={slo} nodes={nodes:.0f} best_tps={best[nodes][0]:.0f}"
            )
    with open(join(directory, "agg-tps.txt"), "w") as f:
        f.write("\n".join(tps_lines) + "\n")


def best_tps_under_slo(out: dict, slo_ms: float) -> dict[float, tuple]:
    """Per committee size, the best (tps_mean, tps_stdev) among fault-free
    setups whose mean e2e latency stays under `slo_ms` — the selection rule
    behind both agg-tps.txt and the tps-vs-committee plot."""
    best: dict[float, tuple] = {}
    for (nodes, faults, tx_size, rate), agg in out.items():
        if faults:
            continue
        if agg["e2e_latency"]["mean"] <= slo_ms and (
            nodes not in best or agg["e2e_tps"]["mean"] > best[nodes][0]
        ):
            best[nodes] = (agg["e2e_tps"]["mean"], agg["e2e_tps"]["stdev"])
    return best
