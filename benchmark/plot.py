"""Result plotting (reference benchmark/benchmark/plot.py:56-164): the three
reference plot families, each as errorbar plots with dual tx/s / MB/s axes:

  * latency-vs-throughput  — the L-graph, one line per committee size
  * tps-vs-committee-size  — best throughput under a max-latency SLO
  * robustness             — achieved throughput vs input rate (saturation
                             collapse), one line per committee size
"""

from __future__ import annotations

from os.path import join

from .aggregate import aggregate_results


def _uniform_tx_size(agg) -> float:
    """The single tx size across all setups, or 0 when sizes are mixed (an
    MB/s axis computed from one of several sizes would mislabel the rest)."""
    sizes = {ts for (_, _, ts, _) in agg}
    return sizes.pop() if len(sizes) == 1 else 0.0


def _mbps_axis(ax, tx_size: float):
    """Secondary x axis in MB/s (the reference's dual tx/s-MB/s axes)."""
    if not tx_size:
        return
    sec = ax.secondary_xaxis(
        "top",
        functions=(
            lambda x: x * tx_size / 1e6,
            lambda x: x * 1e6 / tx_size if tx_size else x,
        ),
    )
    sec.set_xlabel("Throughput (MB/s)")


def _plot_latency(agg, directory, plt) -> str:
    by_nodes: dict[tuple, list] = {}
    tx_size = _uniform_tx_size(agg)
    for (nodes, faults, ts, rate), m in agg.items():
        by_nodes.setdefault((nodes, faults), []).append(
            (
                m["e2e_tps"]["mean"],
                m["e2e_latency"]["mean"],
                m["e2e_latency"]["stdev"],
            )
        )
    fig, ax = plt.subplots(figsize=(6, 4))
    for (nodes, faults), pts in sorted(by_nodes.items()):
        pts.sort()
        label = f"{int(nodes)} nodes" + (f" ({int(faults)} faulty)" if faults else "")
        ax.errorbar(
            [p[0] for p in pts],
            [p[1] / 1000.0 for p in pts],
            yerr=[p[2] / 1000.0 for p in pts],
            marker="o",
            capsize=3,
            label=label,
        )
    ax.set_xlabel("Throughput (tx/s)")
    ax.set_ylabel("Latency (s)")
    ax.legend()
    ax.grid(True, alpha=0.3)
    _mbps_axis(ax, tx_size)
    fig.tight_layout()
    out = join(directory, "latency-vs-throughput.pdf")
    fig.savefig(out)
    plt.close(fig)
    return out


def _plot_tps_vs_committee(agg, directory, plt, max_latency_ms) -> str:
    """Best throughput per committee size whose e2e latency stays under each
    SLO (reference plot.py tps-vs-committee under max-latency)."""
    from .aggregate import best_tps_under_slo

    fig, ax = plt.subplots(figsize=(6, 4))
    for slo in max_latency_ms:
        best = best_tps_under_slo(agg, slo)
        if not best:
            continue
        xs = sorted(best)
        ax.errorbar(
            xs,
            [best[x][0] for x in xs],
            yerr=[best[x][1] for x in xs],
            marker="s",
            capsize=3,
            label=f"latency cap {slo / 1000:.0f} s",
        )
    ax.set_xlabel("Committee size")
    ax.set_ylabel("Throughput (tx/s)")
    ax.legend()
    ax.grid(True, alpha=0.3)
    fig.tight_layout()
    out = join(directory, "tps-vs-committee.pdf")
    fig.savefig(out)
    plt.close(fig)
    return out


def _plot_robustness(agg, directory, plt) -> str:
    by_nodes: dict[tuple, list] = {}
    tx_size = _uniform_tx_size(agg)
    for (nodes, faults, ts, rate), m in agg.items():
        by_nodes.setdefault((nodes, faults), []).append(
            (rate, m["e2e_tps"]["mean"], m["e2e_tps"]["stdev"])
        )
    fig, ax = plt.subplots(figsize=(6, 4))
    for (nodes, faults), pts in sorted(by_nodes.items()):
        pts.sort()
        label = f"{int(nodes)} nodes" + (f" ({int(faults)} faulty)" if faults else "")
        ax.errorbar(
            [p[0] for p in pts],
            [p[1] for p in pts],
            yerr=[p[2] for p in pts],
            marker="x",
            capsize=3,
            label=label,
        )
    lo, hi = ax.get_xlim()
    ax.plot([lo, hi], [lo, hi], ls=":", c="gray", label="ideal")
    ax.set_xlabel("Input rate (tx/s)")
    ax.set_ylabel("Throughput (tx/s)")
    ax.legend()
    ax.grid(True, alpha=0.3)
    _mbps_axis(ax, tx_size)
    fig.tight_layout()
    out = join(directory, "robustness.pdf")
    fig.savefig(out)
    plt.close(fig)
    return out


def plot_results(
    directory: str = "results", max_latency_ms=(2_000, 5_000)
) -> list[str]:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    agg = aggregate_results(directory)
    if not agg:
        print(f"no result files in {directory}")
        return []
    outs = [
        _plot_latency(agg, directory, plt),
        _plot_tps_vs_committee(agg, directory, plt, max_latency_ms),
        _plot_robustness(agg, directory, plt),
    ]
    for o in outs:
        print(f"wrote {o}")
    return outs
