"""Result plotting (reference benchmark/benchmark/plot.py:56-164):
latency-vs-throughput and throughput-vs-committee-size errorbar plots with
dual tx/s / MB/s axes.
"""

from __future__ import annotations

from glob import glob
from os.path import join

from .aggregate import aggregate_results


def plot_results(directory: str = "results") -> None:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    agg = aggregate_results(directory)
    if not agg:
        print(f"no result files in {directory}")
        return

    # Latency vs throughput, one line per committee size.
    by_nodes: dict[float, list] = {}
    for (nodes, faults, tx_size, rate), metrics in agg.items():
        by_nodes.setdefault(nodes, []).append(
            (
                metrics["e2e_tps"]["mean"],
                metrics["e2e_latency"]["mean"],
                metrics["e2e_tps"]["stdev"],
                metrics["e2e_latency"]["stdev"],
            )
        )
    fig, ax = plt.subplots(figsize=(6, 4))
    for nodes, pts in sorted(by_nodes.items()):
        pts.sort()
        xs = [p[0] for p in pts]
        ys = [p[1] / 1000.0 for p in pts]
        yerr = [p[3] / 1000.0 for p in pts]
        ax.errorbar(xs, ys, yerr=yerr, marker="o", capsize=3, label=f"{int(nodes)} nodes")
    ax.set_xlabel("Throughput (tx/s)")
    ax.set_ylabel("Latency (s)")
    ax.legend()
    ax.grid(True, alpha=0.3)
    fig.tight_layout()
    out = join(directory, "latency-vs-throughput.pdf")
    fig.savefig(out)
    print(f"wrote {out}")
