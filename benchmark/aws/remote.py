"""Remote (multi-host) benchmark orchestration (reference benchmark/aws/remote.py:53-301).

install -> update -> config -> run sweep (nodes x rate x runs) -> download +
parse logs. Requires fabric (ssh) + boto3; imports are lazy so the rest of the
harness works without them.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from os.path import basename, join, splitext

from ..commands import CommandMaker
from ..config import BenchParameters, NodeParameters
from ..logs import LogParser
from .instance import InstanceManager
from .settings import Settings


class BenchError(Exception):
    pass


class Bench:
    def __init__(self, settings_file: str = "settings.json") -> None:
        try:
            from fabric import Connection  # noqa: F401
        except ImportError as e:  # pragma: no cover
            raise BenchError("fabric is required for remote benchmarks") from e
        self.settings = Settings.load(settings_file)
        self.manager = InstanceManager(self.settings)

    def _connect(self, host: str):
        from fabric import Connection

        return Connection(
            host, user="ubuntu", connect_kwargs={"key_filename": self.settings.key_path}
        )

    def _run_on(self, hosts: list[str], command: str) -> None:
        for host in hosts:
            self._connect(host).run(command, hide=True)

    def install(self) -> None:
        """Install the framework on all hosts (remote.py:79-110)."""
        cmd = " && ".join(
            [
                "sudo apt-get update",
                "sudo apt-get -y install python3-pip git",
                f"(git clone {self.settings.repo_url} || true)",
                f"cd {self.settings.repo_name} && git checkout {self.settings.branch}",
                "pip3 install -e . || true",
            ]
        )
        hosts = self.manager.hosts(flat=True)
        self._run_on(hosts, cmd)
        print(f"installed on {len(hosts)} hosts")

    def _update(self, hosts: list[str]) -> None:
        cmd = (
            f"cd {self.settings.repo_name} && git fetch -f && "
            f"git checkout -f {self.settings.branch} && git pull -f"
        )
        self._run_on(hosts, cmd)

    def _config(self, hosts: list[str], node_params: NodeParameters) -> list[str]:
        """Generate keys/committee locally and upload (remote.py:154-199)."""
        import json
        import subprocess

        names = []
        key_files = []
        for i, _host in enumerate(hosts):
            f = f".node-{i}.json"
            subprocess.run(
                CommandMaker.generate_key(f), shell=True, check=True,
                capture_output=True,
            )
            from ..config import Key

            names.append(Key.from_file(f).name)
            key_files.append(f)

        committee = {
            "consensus": {
                "epoch": 1,
                "authorities": {
                    n: {"stake": 1, "address": f"{h}:{self.settings.base_port}"}
                    for n, h in zip(names, hosts)
                },
            },
            "mempool": {
                "epoch": 1,
                "authorities": {
                    n: {
                        "front_address": f"{h}:{self.settings.front_port}",
                        "mempool_address": f"{h}:{self.settings.mempool_port}",
                    }
                    for n, h in zip(names, hosts)
                },
            },
        }
        with open(".committee.json", "w") as f:
            json.dump(committee, f, indent=2)
        node_params.write(".parameters.json")

        for i, host in enumerate(hosts):
            c = self._connect(host)
            c.run(f"rm -f {self.settings.repo_name}/.*.json", warn=True, hide=True)
            for f in (key_files[i], ".committee.json", ".parameters.json"):
                c.put(f, join(self.settings.repo_name, basename(f)))
        return key_files

    def _run_single(
        self,
        hosts: list[str],
        rate: int,
        bench: BenchParameters,
        debug: bool,
        crypto: str = "cpu",
    ) -> None:
        """Launch nodes + clients over ssh (remote.py:200-247). With
        crypto="tpu", each host boots its own crypto sidecar (one
        accelerator per host) and the node connects as a remote client —
        the same wiring LocalBench uses on one machine."""
        self._run_on(hosts, CommandMaker.kill())  # clear stale node/sidecar procs
        boot = hosts[: len(hosts) - bench.faults]
        per_client_rate = max(1, rate // len(boot))
        consensus_addrs = [f"{h}:{self.settings.base_port}" for h in boot]
        sidecar_port = self.settings.base_port - 100
        for i, host in enumerate(boot):
            c = self._connect(host)
            if crypto == "tpu":
                sidecar_cmd = CommandMaker.run_sidecar(sidecar_port, "tpu", debug=debug)
                c.run(
                    f"cd {self.settings.repo_name} && "
                    f"nohup {sidecar_cmd} > sidecar.log 2>&1 &",
                    hide=True,
                )
                # Nodes silently fall back to CPU if the sidecar is not up,
                # which would record CPU numbers as a "tpu" run — wait for
                # the readiness line like LocalBench does (local.py:96-111).
                deadline = time.time() + 480
                while time.time() < deadline:
                    r = c.run(
                        f"grep -l 'successfully booted' "
                        f"{self.settings.repo_name}/sidecar.log || true",
                        hide=True,
                    )
                    if r.stdout.strip():
                        break
                    time.sleep(5)
                else:
                    raise BenchError(f"crypto sidecar on {host} never booted")
            node_cmd = CommandMaker.run_node(
                f".node-{i}.json", ".committee.json", ".db/log", ".parameters.json",
                crypto="remote" if crypto == "tpu" else crypto,
                crypto_addr=f"127.0.0.1:{sidecar_port}" if crypto == "tpu" else None,
                debug=debug,
            )
            client_cmd = CommandMaker.run_client(
                f"{host}:{self.settings.front_port}",
                bench.tx_size,
                per_client_rate,
                consensus_addrs,
            )
            c.run(
                f"cd {self.settings.repo_name} && "
                f"nohup {node_cmd} > node.log 2>&1 &",
                hide=True,
            )
            c.run(
                f"cd {self.settings.repo_name} && "
                f"nohup {client_cmd} > client.log 2>&1 &",
                hide=True,
            )
        time.sleep(bench.duration)
        self._run_on(hosts, CommandMaker.kill())

    def _logs(self, hosts: list[str], faults: int) -> LogParser:
        import subprocess

        subprocess.run(CommandMaker.clean_logs(), shell=True, check=True)
        for i, host in enumerate(hosts):
            c = self._connect(host)
            c.get(join(self.settings.repo_name, "node.log"), f"logs/node-{i}.log")
            c.get(join(self.settings.repo_name, "client.log"), f"logs/client-{i}.log")
            try:
                c.get(
                    join(self.settings.repo_name, "sidecar.log"),
                    f"logs/sidecar-{i}.log",
                )
            except OSError:
                pass  # cpu runs have no sidecar
        return LogParser.process("logs", faults)

    def run(
        self,
        bench_params: dict,
        node_params: dict,
        debug: bool = False,
        crypto: str = "cpu",
    ) -> None:
        """Full sweep: nodes x rate x runs (remote.py:249-301)."""
        bench = BenchParameters(bench_params)
        params = NodeParameters(node_params)
        all_hosts = self.manager.hosts(flat=True)
        for n in bench.nodes:
            hosts = all_hosts[:n]
            if len(hosts) < n:
                raise BenchError(f"only {len(hosts)} hosts available, need {n}")
            self._update(hosts)
            self._config(hosts, params)
            for rate in bench.rate:
                for run_idx in range(bench.runs):
                    print(f"run {run_idx}: {n} nodes @ {rate} tx/s")
                    self._run_single(hosts, rate, bench, debug, crypto=crypto)
                    parser = self._logs(hosts, bench.faults)
                    fname = f"results/bench-{n}-{rate}-{bench.tx_size}-{bench.faults}.txt"
                    with open(fname, "a") as f:
                        f.write(parser.result())
