"""AWS EC2 testbed lifecycle (reference benchmark/aws/instance.py:18-268).

Multi-region instance create/start/stop/terminate/info with a security group
opening the consensus/mempool/front ports. Requires boto3 (not installed in
this environment; the module imports it lazily).
"""

from __future__ import annotations

from .settings import Settings


class AWSError(Exception):
    pass


class InstanceManager:
    SECURITY_GROUP_NAME = "hotstuff-tpu"
    INSTANCE_NAME = "hotstuff-tpu-node"

    def __init__(self, settings: Settings) -> None:
        try:
            import boto3
        except ImportError as e:  # pragma: no cover
            raise AWSError("boto3 is required for AWS testbeds") from e
        self.settings = settings
        self.clients = {
            region: boto3.client("ec2", region_name=region)
            for region in settings.aws_regions
        }

    @classmethod
    def make(cls, settings_file: str = "settings.json") -> "InstanceManager":
        return cls(Settings.load(settings_file))

    def _security_group(self, client) -> None:
        sg_rules = [
            {
                "IpProtocol": "tcp",
                "FromPort": port,
                "ToPort": port,
                "IpRanges": [{"CidrIp": "0.0.0.0/0"}],
            }
            for port in (
                22,
                self.settings.base_port,
                self.settings.mempool_port,
                self.settings.front_port,
            )
        ]
        try:
            client.create_security_group(
                GroupName=self.SECURITY_GROUP_NAME,
                Description="hotstuff-tpu benchmark testbed",
            )
            client.authorize_security_group_ingress(
                GroupName=self.SECURITY_GROUP_NAME, IpPermissions=sg_rules
            )
        except client.exceptions.ClientError as e:
            if "InvalidGroup.Duplicate" not in str(e):
                raise

    def _get_ami(self, client) -> str:
        # Latest Ubuntu 22.04 LTS amd64 image in the region.
        images = client.describe_images(
            Filters=[
                {
                    "Name": "name",
                    "Values": ["ubuntu/images/hvm-ssd/ubuntu-jammy-22.04-amd64-server-*"],
                },
                {"Name": "state", "Values": ["available"]},
            ],
            Owners=["099720109477"],
        )["Images"]
        if not images:
            raise AWSError("no Ubuntu AMI found")
        return max(images, key=lambda im: im["CreationDate"])["ImageId"]

    def create_instances(self, per_region: int) -> None:
        for region, client in self.clients.items():
            self._security_group(client)
            client.run_instances(
                ImageId=self._get_ami(client),
                InstanceType=self.settings.instance_type,
                KeyName=self.settings.key_name,
                MinCount=per_region,
                MaxCount=per_region,
                SecurityGroups=[self.SECURITY_GROUP_NAME],
                TagSpecifications=[
                    {
                        "ResourceType": "instance",
                        "Tags": [{"Key": "Name", "Value": self.INSTANCE_NAME}],
                    }
                ],
                BlockDeviceMappings=[
                    {
                        "DeviceName": "/dev/sda1",
                        "Ebs": {"VolumeSize": 200, "VolumeType": "gp3"},
                    }
                ],
            )
            print(f"created {per_region} instances in {region}")

    def _instances(self, client, states: list[str]):
        out = client.describe_instances(
            Filters=[
                {"Name": "tag:Name", "Values": [self.INSTANCE_NAME]},
                {"Name": "instance-state-name", "Values": states},
            ]
        )
        for res in out["Reservations"]:
            yield from res["Instances"]

    def _apply(self, action: str, states: list[str]) -> None:
        for region, client in self.clients.items():
            ids = [i["InstanceId"] for i in self._instances(client, states)]
            if not ids:
                continue
            getattr(client, action)(InstanceIds=ids)
            print(f"{action} {len(ids)} instances in {region}")

    def start_instances(self) -> None:
        self._apply("start_instances", ["stopped"])

    def stop_instances(self) -> None:
        self._apply("stop_instances", ["running", "pending"])

    def terminate_instances(self) -> None:
        self._apply(
            "terminate_instances", ["running", "pending", "stopping", "stopped"]
        )

    def hosts(self, flat: bool = False):
        out = {}
        for region, client in self.clients.items():
            out[region] = [
                i.get("PublicIpAddress")
                for i in self._instances(client, ["running"])
                if i.get("PublicIpAddress")
            ]
        if flat:
            return [ip for ips in out.values() for ip in ips]
        return out

    def print_info(self) -> None:
        for region, ips in self.hosts().items():
            print(f"{region}: {len(ips)} running")
            for ip in ips:
                print(f"  ssh -i {self.settings.key_path} ubuntu@{ip}")
