"""Testbed settings loader (reference benchmark/aws/settings.py:8-60)."""

from __future__ import annotations

import json


class SettingsError(Exception):
    pass


class Settings:
    def __init__(self, obj: dict) -> None:
        try:
            self.key_name = obj["key"]["name"]
            self.key_path = obj["key"]["path"]
            self.base_port = int(obj["ports"]["consensus"])
            self.mempool_port = int(obj["ports"]["mempool"])
            self.front_port = int(obj["ports"]["front"])
            self.repo_name = obj["repo"]["name"]
            self.repo_url = obj["repo"]["url"]
            self.branch = obj["repo"]["branch"]
            self.instance_type = obj["instances"]["type"]
            self.aws_regions = obj["instances"]["regions"]
        except (KeyError, ValueError, TypeError) as e:
            raise SettingsError(f"malformed settings: {e}") from e

    @classmethod
    def load(cls, filename: str = "settings.json") -> "Settings":
        try:
            with open(filename) as f:
                return cls(json.load(f))
        except (OSError, json.JSONDecodeError) as e:
            raise SettingsError(str(e)) from e
