"""Repeated-run driver: N local benchmark runs of one config, aggregated.

The reference's committed result files hold repeated runs appended to one
file (e.g. data/2-chain/results/bench-10-70000-512-0.txt), and its
aggregate.py averages them. Here each run writes its own
``bench-<nodes>-<rate>-<size>-<faults>-run<i>.txt`` into ``--outdir`` and
``benchmark.aggregate`` produces mean ± stdev with real run counts — on a
noisy shared host single-run numbers are not evidence.

    python -m benchmark.multirun --nodes 4 --rate 3000 --size 512 \
        --duration 120 --runs 3 --crypto cpu --benchmark-workload \
        --outdir data/local/multirun_r05

Runs are sequential (1 vCPU: concurrent committees corrupt each other's
timings) with a settle pause between them.

NOTE: the aggregator groups by (nodes, faults, tx_size, rate) only — runs
of the same shape with different crypto backends or workload flags must go
in SEPARATE --outdir directories or they average together.
"""

from __future__ import annotations

import argparse
import os
import time
from os.path import join

from .aggregate import aggregate_results
from .fabfile import LOCAL_NODE_PARAMS
from .local import BenchError, LocalBench


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument("--rate", type=int, default=1_000)
    p.add_argument("--size", type=int, default=512)
    p.add_argument("--faults", type=int, default=0)
    p.add_argument("--duration", type=int, default=60)
    p.add_argument("--runs", type=int, default=3)
    p.add_argument("--crypto", default="cpu", choices=["cpu", "tpu"])
    p.add_argument("--benchmark-workload", action="store_true")
    p.add_argument("--mempool-payload-size", type=int, default=None,
                   help="override mempool max_payload_size (bytes)")
    p.add_argument("--timeout-delay", type=int, default=None)
    p.add_argument("--outdir", default="data/local/multirun")
    p.add_argument("--tag", default="",
                   help="suffix for result filenames (e.g. 'tpu-workload')")
    p.add_argument("--settle", type=int, default=5,
                   help="seconds between runs (let sockets/process slots drain)")
    args = p.parse_args()

    os.makedirs(args.outdir, exist_ok=True)
    bench_params = {
        "nodes": args.nodes,
        "rate": args.rate,
        "tx_size": args.size,
        "faults": args.faults,
        "duration": args.duration,
        "crypto": args.crypto,
    }
    node_params = {k: dict(v) for k, v in LOCAL_NODE_PARAMS.items()}
    if args.benchmark_workload:
        node_params["mempool"]["benchmark_mode"] = True
    if args.mempool_payload_size is not None:
        node_params["mempool"]["max_payload_size"] = args.mempool_payload_size
    if args.timeout_delay is not None:
        node_params["consensus"]["timeout_delay"] = args.timeout_delay

    tag = f"-{args.tag}" if args.tag else ""
    done = 0
    for i in range(args.runs):
        name = (
            f"bench-{args.nodes}-{args.rate}-{args.size}-{args.faults}"
            f"{tag}-run{i}.txt"
        )
        print(f"--- run {i + 1}/{args.runs}: {name}")
        try:
            parser = LocalBench(bench_params, node_params).run()
        except BenchError as e:
            # One failed run must not discard the others; the aggregate's
            # run count states how many succeeded.
            print(f"run {i} failed: {e}")
            continue
        result = parser.result()
        print(result)
        with open(join(args.outdir, name), "w") as f:
            f.write(result)
        done += 1
        if i + 1 < args.runs:
            time.sleep(args.settle)

    if done:
        aggregate_results(args.outdir)
        print(f"aggregated {done}/{args.runs} runs into {args.outdir}/aggregated.txt")
    else:
        raise SystemExit("every run failed")


if __name__ == "__main__":
    main()
